"""Collective communication API.

Reference architecture (SURVEY.md §2.9, §3.5): python paddle.distributed.* →
communication/stream/* → pybind → ProcessGroupNCCL → NCCLCommContext →
ncclAllReduce, with TCPStore bootstrap and per-ring comm contexts.

TPU-native redesign: the transport is XLA collectives over ICI/DCN. A Group is
a 1-D device mesh axis; each eager collective jit-compiles a shard_map whose
body is the XLA collective (psum/all_gather/ppermute/all_to_all) — the
ProcessGroup/CommContext/NCCL stack collapses into the compiler's collective
emission, and the executable cache plays the role of the comm-op cache.

Two execution modes, auto-detected from ``jax.process_count()``:

* **Single-controller** (1 process, N devices): a tensor participating in an
  eager collective is RANK-STACKED — dim 0 indexes the group's ranks (the
  analog of each rank's local tensor in the reference's multi-process world;
  the reference's own single-host multi-rank tests, test/collective/, are the
  model).
* **Multi-process** (a real ``jax.distributed`` world, rank == process, as
  bootstrapped by ``init_parallel_env`` from the launcher's env): tensors are
  PROCESS-LOCAL, exactly the reference's semantics
  (``process_group.h:47`` — each rank passes its local tensor and receives
  its local result). The same shard_map bodies run over a one-device-per-
  process mesh; XLA's CPU Gloo / TPU ICI transport carries the bytes.

In-graph (jit/TrainStep) code should instead rely on sharding annotations,
where GSPMD inserts collectives automatically.
"""
from __future__ import annotations

import os
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from .auto_parallel import ProcessMesh

try:
    from jax import shard_map as _shard_map  # jax >= 0.8
    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
except (ImportError, TypeError):  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_old
    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)


#: jaxpr primitive names that are cross-rank collectives. This is the
#: canonical set the static analyzer keys on (analysis/dataflow.py rule
#: DF004, collective-ordering lint): every mesh axis must observe an
#: identical sequence of these primitives on all ranks or the mesh
#: deadlocks. Keep in sync with the lax collectives the eager API below
#: emits through its shard_map bodies.
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter", "pbroadcast",
})


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _mp() -> bool:
    """True in a real multi-process world (rank == process, reference
    semantics); False under the single-controller rank-stacked convention."""
    return jax.process_count() > 1


class Group:
    """Process group = 1-D mesh axis (process_group.h:47 analog)."""

    _next_id = [0]

    def __init__(self, ranks: List[int], mesh: ProcessMesh, axis_name: str):
        self.ranks = list(ranks)
        self.nranks = len(ranks)
        self.mesh = mesh
        self.axis_name = axis_name
        self.id = Group._next_id[0]
        Group._next_id[0] += 1
        self._eager_mesh = None

    @property
    def world_size(self):
        return self.nranks

    @property
    def rank(self):
        if _mp():
            return self.get_group_rank(jax.process_index())
        return 0  # single-controller SPMD: one logical program

    @property
    def rank_in_group(self):
        return self.rank

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def _collective_mesh(self):
        """Mesh the eager collectives run over.

        Multi-process: one device per member process (rank == process, as the
        reference's ProcessGroup does); only member processes participate.
        Single-controller: the group's full device mesh.
        """
        if not _mp():
            return self.mesh.jax_mesh
        if self._eager_mesh is None:
            by_proc = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, d)
            devs = np.array([by_proc[r] for r in self.ranks], dtype=object)
            self._eager_mesh = jax.sharding.Mesh(devs, (self.axis_name,))
        return self._eager_mesh

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


_WORLD: List[Optional[Group]] = [None]


_BOOTSTRAP = {"store": None}


def _maybe_init_multihost():
    """Multi-host bootstrap (parallel.py:943's TCPStore + comm-context
    creation, TPU-shaped): when the launcher's env says this is a
    multi-process job, initialize the PJRT distributed runtime (ICI/DCN
    plane) and open the TCPStore control plane (barriers, elastic,
    checkpoint coordination) against rank 0."""
    import os
    nnodes = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    coord = os.environ.get("PADDLE_MASTER",
                           os.environ.get("MASTER_ENDPOINT"))
    if nnodes <= 1 or not coord or _BOOTSTRAP["store"] is not None:
        return
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    # the launcher normalizes PADDLE_MASTER to an http:// KV endpoint and
    # publishes the real gRPC coordinator as JAX_COORDINATOR_ADDRESS
    # (launch/controllers.py) — strip the scheme for our own parsing
    coord = coord.split("://", 1)[-1]
    if ":" not in coord:
        raise ValueError(f"PADDLE_MASTER must be host:port, got {coord!r}")
    host, port = coord.rsplit(":", 1)
    coord_addr = os.environ.get("JAX_COORDINATOR_ADDRESS",
                                f"{host}:{int(port) + 1}")
    # the CPU PJRT client has no cross-process collectives of its own —
    # without gloo every multi-process CPU-proxy run dies at the first
    # collective with "Multiprocess computations aren't implemented on
    # the CPU backend". Must be set BEFORE the backend is created, so
    # key off the platform request rather than jax.default_backend().
    platforms = (os.environ.get("JAX_PLATFORMS")
                 or getattr(jax.config, "jax_platforms", None) or "")
    if "cpu" in platforms.split(","):
        try:
            jax.config.update("jax_cpu_enable_gloo_collectives", True)
        except Exception:
            pass  # flag absent on this jaxlib: keep the TPU path intact
    try:
        # num_processes/process_id must be explicit: jax only reads the
        # coordinator address from env, not the process counts
        jax.distributed.initialize(coordinator_address=coord_addr,
                                   num_processes=nnodes, process_id=rank)
    except RuntimeError as e:
        if "already" not in str(e).lower():
            raise  # real failure: do NOT proceed as N separate jobs
    from ..core.native import TCPStore
    # control plane: master+2 (master = launcher KV, master+1 = PJRT
    # coordinator, see launch/main.py port layout)
    store = TCPStore(host, int(port) + 2, is_master=(rank == 0),
                     world_size=nnodes)
    # publish only once the whole world has arrived — a failed barrier must
    # not leave a half-initialized bootstrap behind
    store.barrier("init_parallel_env", world_size=nnodes)
    _BOOTSTRAP["store"] = store


def get_bootstrap_store():
    """The job-wide TCPStore (None in single-process runs)."""
    return _BOOTSTRAP["store"]


def init_parallel_env(strategy=None) -> Optional[Group]:
    """distributed.init_parallel_env (parallel.py:943 analog). Builds the
    world group over all visible devices (ICI-connected on a TPU slice);
    multi-host jobs additionally bootstrap the PJRT distributed runtime and
    the TCPStore control plane from the launcher's env."""
    if _WORLD[0] is None:
        _maybe_init_multihost()
        n = len(jax.devices())
        mesh = ProcessMesh(np.arange(n), ["world"])
        if _mp():
            # rank == process (reference trainer semantics); the mesh still
            # spans every device for in-graph GSPMD use
            ranks = list(range(jax.process_count()))
        else:
            ranks = list(range(n))
        _WORLD[0] = Group(ranks, mesh, "world")
        if _mp() and os.environ.get("PADDLE_COLLECTIVE_WATCHDOG") == "1":
            # opt-in auto-arm (launcher propagates env to every rank):
            # desync diagnosis without touching user code
            from .watchdog import enable_collective_watchdog
            enable_collective_watchdog(timeout=float(os.environ.get(
                "PADDLE_COLLECTIVE_WATCHDOG_TIMEOUT", "300")))
    return _WORLD[0]


def is_initialized() -> bool:
    return _WORLD[0] is not None


def _world() -> Group:
    if _WORLD[0] is None:
        init_parallel_env()
    return _WORLD[0]


def get_world_size(group: Optional[Group] = None) -> int:
    return (group or _world()).nranks


def get_rank(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.rank if _mp() else jax.process_index()
    return jax.process_index()


def new_group(ranks: Optional[List[int]] = None, backend=None,
              timeout=None) -> Group:
    """distributed.new_group (collective.py:180 analog)."""
    if ranks is None:
        # multi-process: rank space is processes, not devices
        ranks = list(range(jax.process_count() if _mp()
                           else len(jax.devices())))
    mesh = ProcessMesh(np.asarray(ranks), ["g"])
    return Group(ranks, mesh, "g")


def destroy_process_group(group=None):
    if group is None or group is _WORLD[0]:
        _WORLD[0] = None



_COLL_METRICS = [None]  # lazy (calls, bytes, seconds) families


def _coll_metrics():
    fams = _COLL_METRICS[0]
    if fams is None:
        from ..observability.metrics import get_registry
        reg = get_registry()
        fams = (
            reg.counter("collective_calls_total",
                        "collective invocations by op", labelnames=("op",)),
            reg.counter("collective_bytes_total",
                        "tensor payload bytes entering collectives by op",
                        labelnames=("op",)),
            reg.histogram("collective_seconds",
                          "collective wall time by op (host-side, includes "
                          "dispatch + any blocking)", labelnames=("op",)),
        )
        _COLL_METRICS[0] = fams
    return fams


def _watched(name):
    """Wrap a collective entry point with telemetry (per-op call/bytes
    counters + latency histogram, always on) and the desync watchdog
    (no-op — one attribute read — unless enable_collective_watchdog
    armed it)."""
    import functools
    import time as _time

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            calls, bytes_c, seconds = _coll_metrics()
            calls.labels(op=name).inc()
            t = next((a for a in args if hasattr(a, "shape")), None)
            nb = 0
            if t is not None:
                nb = getattr(getattr(t, "_data", t), "nbytes", 0)
                if nb:
                    bytes_c.labels(op=name).inc(int(nb))
            # per-mesh-axis twins, ONLY under an armed mesh.axis_scope:
            # single-process output stays byte-identical (the twin
            # families are never even created without a scope)
            from .mesh import current_axis_label
            axis = current_axis_label()
            if axis is not None:
                from ..observability.metrics import get_registry
                reg = get_registry()
                reg.counter("collective_axis_calls_total",
                            "collective invocations by op and mesh axis",
                            labelnames=("op", "axis")).labels(
                                op=name, axis=axis).inc()
                if nb:
                    reg.counter(
                        "collective_axis_bytes_total",
                        "tensor payload bytes entering collectives by op "
                        "and mesh axis",
                        labelnames=("op", "axis")).labels(
                            op=name, axis=axis).inc(int(nb))
            from ..observability import fleet as _fleet
            # fleet enter BEFORE the fault point: a kill_rank here leaves
            # the enter-without-exit signature in the victim's shard/ring
            tok = _fleet.on_collective_enter(name)
            from ..resilience.chaos import fault_point
            fault_point("collective.enter")  # chaos drills; no-op unarmed
            t0 = _time.perf_counter()
            try:
                from . import watchdog as _wd
                if _wd.get_watchdog() is None:
                    return fn(*args, **kwargs)
                with _wd.watch(name, t):
                    return fn(*args, **kwargs)
            finally:
                seconds.labels(op=name).observe(_time.perf_counter() - t0)
                _fleet.on_collective_exit(tok, name)
        return wrapper
    return deco


@_watched("barrier")
def barrier(group: Optional[Group] = None):
    g = group or _world()
    x = jnp.zeros((1,) if _mp() else (g.nranks,), jnp.int32)
    _stacked(lambda v: jax.lax.psum(v, g.axis_name), g, x,
             cache_key=("barrier",)).block_until_ready()


# -- stacked collective machinery -------------------------------------------

_STACKED_JIT_CACHE: dict = {}


def _stacked(body, group: Group, arr, out_sharded=True, cache_key=None):
    """Run `body` per-rank-shard over the group axis via shard_map.

    Single-controller: `arr` is rank-stacked [nranks, ...]; the stacked
    result comes back. Multi-process: `arr` is this process's LOCAL slot
    [...]; it is lifted to one row of the global array
    (make_array_from_process_local_data), the same body runs SPMD across
    processes, and the local row (or the replicated whole, for
    out_sharded=False) comes back.

    cache_key (hashable, identifying the body's semantics) lets repeat eager
    collectives reuse one jitted callable instead of re-wrapping a fresh
    lambda in jax.jit every call (which defeats jit's identity cache)."""
    mesh = group._collective_mesh()
    in_spec = P(group.axis_name)
    out_spec = P(group.axis_name) if out_sharded else P()
    if cache_key is not None:
        key = (mesh, group.axis_name, out_sharded, cache_key)
        fn = _STACKED_JIT_CACHE.get(key)
        if fn is None:
            fn = jax.jit(shard_map(body, mesh, (in_spec,), out_spec))
            _STACKED_JIT_CACHE[key] = fn
    else:
        fn = jax.jit(shard_map(body, mesh, (in_spec,), out_spec))
    sharding = NamedSharding(mesh, in_spec)
    if _mp():
        local = np.asarray(arr)[None]
        gshape = (group.nranks,) + tuple(local.shape[1:])
        garr = jax.make_array_from_process_local_data(sharding, local, gshape)
        out = fn(garr)
        if out_sharded:
            return jnp.asarray(out.addressable_data(0))[0]
        return jnp.asarray(out.addressable_data(0))
    if not isinstance(arr, jax.core.Tracer):
        arr = jax.device_put(arr, sharding)
    return fn(arr)


def _unwrap(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


def _check_stacked(arr, group, name):
    if _mp():
        return  # process-local tensors; any shape is this rank's own
    if arr.shape[0] != group.nranks:
        raise ValueError(
            f"{name}: single-controller collectives take rank-stacked tensors "
            f"(dim0 == group size {group.nranks}); got shape {tuple(arr.shape)}")


@_watched("all_reduce")
def all_reduce(tensor, op=ReduceOp.SUM, group: Optional[Group] = None,
               sync_op=True):
    """Each rank slot receives the reduction over all slots
    (ProcessGroupNCCL::AllReduce analog, process_group_nccl.h:103)."""
    g = group or _world()
    arr = _unwrap(tensor)
    _check_stacked(arr, g, "all_reduce")
    red = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
           ReduceOp.MIN: jax.lax.pmin}.get(op)

    if red is not None:
        body = lambda x: red(x, g.axis_name)
    elif op == ReduceOp.AVG:
        body = lambda x: jax.lax.pmean(x, g.axis_name)
    elif op == ReduceOp.PROD:
        # exact product (sign-safe): gather the shards, reduce locally
        body = lambda x: jnp.prod(jax.lax.all_gather(x, g.axis_name), axis=0)
    else:
        raise ValueError(f"unknown reduce op {op}")
    out = _stacked(body, g, arr, cache_key=("all_reduce", op))
    if isinstance(tensor, Tensor):
        tensor._set_data(out)
        return tensor
    return Tensor(out)


@_watched("all_gather")
def all_gather(tensor_list, tensor=None, group: Optional[Group] = None,
               sync_op=True):
    """paddle.distributed.all_gather: append every rank's slice."""
    g = group or _world()
    if tensor is None:
        tensor, tensor_list = tensor_list, None
    arr = _unwrap(tensor)
    _check_stacked(arr, g, "all_gather")
    out = _stacked(
        lambda x: jax.lax.all_gather(x, g.axis_name, axis=0, tiled=True),
        g, arr, out_sharded=False, cache_key=("all_gather",))
    slices = [Tensor(out[i]) for i in range(g.nranks)]
    if tensor_list is not None:
        tensor_list.extend(slices)
        return tensor_list
    return Tensor(out)


_OBJ_SEQ: dict = {}  # per-group sequence: only member ranks advance it


def _obj_store_and_seq(g: Group):
    import pickle  # noqa: F401  (callers use it; import checked here)
    store = get_bootstrap_store()
    if store is None:
        raise RuntimeError(
            "object collectives in a multi-process world need the TCPStore "
            "control plane — launch via paddle_tpu.distributed.launch / "
            "init_parallel_env with PADDLE_MASTER set")
    _OBJ_SEQ[g.id] = _OBJ_SEQ.get(g.id, 0) + 1
    return store, _OBJ_SEQ[g.id]


def _store_all_gather_object(obj, g: Group):
    """Object exchange over the bootstrap TCPStore control plane (the
    reference routes object collectives through tensor serialization +
    NCCL; host-side store exchange is the TPU-shaped equivalent — object
    payloads are control-plane, not ICI-bandwidth, traffic). Keys are
    deleted once the whole group has read them."""
    import pickle
    store, seq = _obj_store_and_seq(g)
    mykey = f"__obj/{g.id}/{seq}/{g.rank}"
    store.set(mykey, pickle.dumps(obj))
    out = []
    for r in range(g.nranks):
        out.append(pickle.loads(store.get(f"__obj/{g.id}/{seq}/{r}")))
    store.barrier(f"__obj/{g.id}/{seq}/done", world_size=g.nranks)
    store.delete_key(mykey)
    return out


def all_gather_object(object_list, obj, group=None):
    g = group or _world()
    if _mp():
        object_list.extend(_store_all_gather_object(obj, g))
        return object_list
    # single controller: every rank slot holds the same object
    object_list.extend([obj] * g.nranks)
    return object_list


@_watched("broadcast")
def broadcast(tensor, src: int = 0, group: Optional[Group] = None,
              sync_op=True):
    g = group or _world()
    arr = _unwrap(tensor)
    _check_stacked(arr, g, "broadcast")
    if src not in g.ranks:
        raise ValueError(f"broadcast: src rank {src} not in group {g.ranks}")
    src_idx = g.get_group_rank(src)

    # close over ints only — a closure over `arr` would pin the first call's
    # device buffer inside the jit cache for process lifetime
    per = 1 if _mp() else arr.shape[0] // g.nranks
    start = src_idx * per

    def body(x, _start=start, _per=per):
        full = jax.lax.all_gather(x, g.axis_name, axis=0, tiled=True)
        return jax.lax.dynamic_slice_in_dim(full, _start, _per, axis=0)

    out = _stacked(body, g, arr,
                   cache_key=("broadcast", src_idx, per))
    if _mp():
        out = out.reshape(arr.shape)
    if isinstance(tensor, Tensor):
        tensor._set_data(out)
        return tensor
    return Tensor(out)


@_watched("reduce")
def reduce(tensor, dst: int = 0, op=ReduceOp.SUM,
           group: Optional[Group] = None, sync_op=True):
    g = group or _world()
    arr = _unwrap(tensor)
    _check_stacked(arr, g, "reduce")
    if dst not in g.ranks:
        raise ValueError(f"reduce: dst rank {dst} not in group {g.ranks}")
    dst_idx = g.get_group_rank(dst)
    if _mp():
        # every member participates in the reduction; only dst keeps it
        summed = all_reduce(Tensor(jnp.asarray(arr)), op, g)
        out = summed._data if g.rank == dst_idx else jnp.asarray(arr)
    else:
        summed = all_reduce(Tensor(arr), op, g).numpy()
        result = np.array(arr)
        result[dst_idx] = summed[dst_idx]
        out = jnp.asarray(result)
    if isinstance(tensor, Tensor):
        tensor._set_data(out)
        return tensor
    return Tensor(out)


@_watched("reduce_scatter")
def reduce_scatter(tensor, tensor_or_tensor_list=None, op=ReduceOp.SUM,
                   group: Optional[Group] = None, sync_op=True):
    """Input stacked [n, n*m, ...]; each rank slot gets its reduced chunk
    [n, m, ...]."""
    g = group or _world()
    if tensor_or_tensor_list is None:
        src = tensor
        out_t = None
    else:
        out_t = tensor
        src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        arr = jnp.stack([_unwrap(t) for t in src], axis=1).reshape(
            (_unwrap(src[0]).shape[0], -1) + tuple(_unwrap(src[0]).shape[2:]))
    else:
        arr = _unwrap(src)
    _check_stacked(arr, g, "reduce_scatter")

    if op == ReduceOp.SUM:
        def body(x):
            return jax.lax.psum_scatter(x[0], g.axis_name,
                                        scatter_dimension=0, tiled=True)[None]
    elif op in (ReduceOp.MAX, ReduceOp.MIN, ReduceOp.AVG):
        red = {ReduceOp.MAX: jax.lax.pmax, ReduceOp.MIN: jax.lax.pmin,
               ReduceOp.AVG: jax.lax.pmean}[op]

        def body(x):
            reduced = red(x[0], g.axis_name)
            chunk = reduced.shape[0] // g.nranks
            idx = jax.lax.axis_index(g.axis_name)
            return jax.lax.dynamic_slice_in_dim(reduced, idx * chunk, chunk,
                                                axis=0)[None]
    else:
        raise ValueError(f"reduce_scatter: unsupported op {op}")

    out = _stacked(body, g, arr, cache_key=("reduce_scatter", op))
    if out_t is not None:
        out_t._set_data(out)
        return out_t
    return Tensor(out)


@_watched("scatter")
def scatter(tensor, tensor_list=None, src: int = 0,
            group: Optional[Group] = None, sync_op=True):
    g = group or _world()
    src_local = g.get_group_rank(src)
    if src_local < 0:
        raise ValueError(f"scatter: src rank {src} not in group {g.ranks}")
    if _mp():
        # tensor = this rank's output buffer; src contributes the real data,
        # everyone else an equal-shaped zero buffer (SPMD participation)
        out_arr = _unwrap(tensor)
        chunk = out_arr.shape[0]
        if g.rank == src_local:
            if tensor_list is None:
                raise ValueError("scatter: the src rank must pass tensor_list")
            contrib = jnp.concatenate([_unwrap(t) for t in tensor_list],
                                      axis=0)
        else:
            contrib = jnp.zeros((g.nranks * chunk,) + tuple(out_arr.shape[1:]),
                                out_arr.dtype)

        def body(x, _s=src_local, _c=chunk):
            full = jax.lax.all_gather(x, g.axis_name, axis=0, tiled=True)
            mine = jax.lax.dynamic_slice_in_dim(full, _s, 1, axis=0)[0]
            idx = jax.lax.axis_index(g.axis_name)
            return jax.lax.dynamic_slice_in_dim(mine, idx * _c, _c,
                                                axis=0)[None]

        out = _stacked(body, g, contrib,
                       cache_key=("scatter_mp", src_local, chunk))
        out = out.reshape(out_arr.shape)
        if isinstance(tensor, Tensor):
            tensor._set_data(out)
            return tensor
        return Tensor(out)
    if tensor_list is not None:
        data = jnp.stack([_unwrap(t)[src_local] for t in tensor_list], axis=0)
    else:
        arr = _unwrap(tensor)
        _check_stacked(arr, g, "scatter")
        chunks = jnp.split(arr[src_local], g.nranks, axis=0)
        data = jnp.stack(chunks, axis=0).reshape(
            (g.nranks,) + tuple(chunks[0].shape))
    if isinstance(tensor, Tensor):
        tensor._set_data(data.reshape(tensor._data.shape)
                         if data.size == tensor.size else data)
        return tensor
    return Tensor(data)


@_watched("alltoall")
def alltoall(in_tensor_list, out_tensor_list=None,
             group: Optional[Group] = None, sync_op=True):
    """all-to-all: out[i][j] = in[j][i] (EP's global_scatter backbone)."""
    g = group or _world()
    if _mp():
        # local input: n chunks (row j goes to rank j); local output: n
        # chunks (row i came from rank i)
        if isinstance(in_tensor_list, (list, tuple)):
            arr = jnp.stack([_unwrap(t) for t in in_tensor_list], axis=0)
        else:
            arr = _unwrap(in_tensor_list)
        if arr.shape[0] != g.nranks:
            raise ValueError(
                f"alltoall: expected {g.nranks} chunks, got {arr.shape[0]}")

        def body(x):
            return jax.lax.all_to_all(x[0], g.axis_name, split_axis=0,
                                      concat_axis=0, tiled=True)[None]

        out = _stacked(body, g, arr, cache_key=("alltoall_mp",))
        if out_tensor_list is not None:
            out_tensor_list.extend(Tensor(out[i]) for i in range(g.nranks))
            return out_tensor_list
        return Tensor(out)
    if isinstance(in_tensor_list, (list, tuple)):
        arr = jnp.stack([_unwrap(t) for t in in_tensor_list], axis=1)
        # arr: [n, n, ...] — [src, dst, ...]
    else:
        arr = _unwrap(in_tensor_list)
        _check_stacked(arr, g, "alltoall")
        arr = arr.reshape((g.nranks, g.nranks, -1) + tuple(arr.shape[2:]))

    out = _stacked(
        lambda x: jax.lax.all_to_all(x, g.axis_name, split_axis=1,
                                     concat_axis=0, tiled=True),
        g, arr, cache_key=("alltoall",))
    if out_tensor_list is not None:
        out_tensor_list.extend(Tensor(out[:, i]) for i in range(g.nranks))
        return out_tensor_list
    return Tensor(out)


def _p2p_exchange(g: Group, arr, src_idx: int, dst_idx: int):
    """Multi-process p2p over a TWO-device mesh spanning only the endpoints,
    so other group members need not participate (the reference's NCCL p2p
    creates a 2-rank communicator the same way,
    pp_utils/p2p_communication.py:52). Send on src and recv on dst must be
    called in matched order — that pairing IS the program."""
    if src_idx == dst_idx:
        return jnp.asarray(arr)
    by_proc = {}
    for d in jax.devices():
        by_proc.setdefault(d.process_index, d)
    pair = (g.ranks[src_idx], g.ranks[dst_idx])
    mesh = jax.sharding.Mesh(
        np.array([by_proc[pair[0]], by_proc[pair[1]]], dtype=object),
        (g.axis_name,))
    key = (mesh, "p2p")
    fn = _STACKED_JIT_CACHE.get(key)
    if fn is None:
        fn = jax.jit(shard_map(
            lambda x: jax.lax.ppermute(x, g.axis_name, [(0, 1)]),
            mesh, (P(g.axis_name),), P(g.axis_name)))
        _STACKED_JIT_CACHE[key] = fn
    sharding = NamedSharding(mesh, P(g.axis_name))
    local = np.asarray(arr)[None]
    garr = jax.make_array_from_process_local_data(
        sharding, local, (2,) + tuple(local.shape[1:]))
    out = fn(garr)
    return jnp.asarray(out.addressable_data(0))[0]


@_watched("send")
def send(tensor, dst: int = 0, group: Optional[Group] = None, sync_op=True):
    """Point-to-point send.

    Multi-process: a ppermute over the group mesh (the matching recv runs
    the same program on the dst rank). Single-controller: data is globally
    addressable, so p2p is a FIFO handoff; in-graph pipeline comm should use
    ppermute (see distributed.ppermute) instead. Matching is FIFO per group —
    ambiguous outstanding sends raise rather than mis-deliver."""
    g = group or _world()
    if dst not in g.ranks:
        raise ValueError(f"send: dst rank {dst} not in group {g.ranks}")
    if _mp():
        _p2p_exchange(g, _unwrap(tensor), g.rank, g.get_group_rank(dst))
        return
    _P2P_BUF.setdefault(g.id, []).append((dst, _unwrap(tensor)))


@_watched("recv")
def recv(tensor, src: int = 0, group: Optional[Group] = None, sync_op=True):
    g = group or _world()
    if src not in g.ranks:
        raise ValueError(f"recv: src rank {src} not in group {g.ranks}")
    if _mp():
        out = _p2p_exchange(g, _unwrap(tensor), g.get_group_rank(src), g.rank)
        tensor._set_data(out.reshape(tensor._data.shape))
        return tensor
    buf = _P2P_BUF.get(g.id, [])
    if not buf:
        raise RuntimeError("recv without matching send")
    if len(buf) > 1:
        raise RuntimeError(
            "ambiguous p2p matching: multiple outstanding sends in this group "
            "under the single-controller FIFO model; use in-graph ppermute "
            "for pipelined p2p schedules")
    _, data = buf.pop(0)
    tensor._set_data(jnp.asarray(data).reshape(tensor._data.shape))
    return tensor


_P2P_BUF: dict = {}

isend = send
irecv = recv


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    for op in p2p_op_list:
        op.op(op.tensor, op.peer, op.group)
    return []


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        tensor._data.block_until_ready()


# -- in-graph primitives (for shard_map'd custom parallel code) -------------

def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name):
    return jax.lax.pmean(x, axis_name)


def ppermute(x, axis_name, perm):
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name):
    return jax.lax.axis_index(axis_name)


@_watched("gather")
def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """paddle.distributed.gather: rank `dst` receives every slice (single
    controller: all_gather then keep; non-dst ranks get an empty list)."""
    g = group or _world()
    slices = all_gather([], tensor, group=g)  # returns the per-rank list
    if gather_list is not None:
        gather_list.extend(slices)
        return gather_list
    return slices


@_watched("alltoall_single")
def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """paddle.distributed.alltoall_single. Equal splits run in both modes;
    RAGGED splits (in/out_split_sizes) run in a real multi-process world
    (_ragged_alltoall_single: pad-to-global-max over the tiled all_to_all)
    — the single-controller rank-stacked convention cannot express
    per-rank sizes and raises."""
    g = group or _world()
    arr = _unwrap(in_tensor)
    n = g.nranks
    if in_split_sizes is not None or out_split_sizes is not None:
        if not _mp():
            raise NotImplementedError(
                "ragged alltoall_single needs a real multi-process world "
                "(per-rank tensor sizes differ; the single-controller "
                "rank-stacked convention cannot express them)")
        return _ragged_alltoall_single(arr, in_tensor, out_tensor,
                                       in_split_sizes, out_split_sizes, g)
    if _mp():
        if arr.shape[0] % n:
            raise ValueError(
                f"alltoall_single: dim0 {arr.shape[0]} not divisible by "
                f"group size {n}")
        chunks = arr.reshape((n, arr.shape[0] // n) + tuple(arr.shape[1:]))

        def body(x):
            return jax.lax.all_to_all(x[0], g.axis_name, split_axis=0,
                                      concat_axis=0, tiled=True)[None]

        out = _stacked(body, g, chunks, cache_key=("alltoall_single_mp",))
        result = Tensor(out.reshape(arr.shape))
        if out_tensor is not None:
            out_tensor._set_data(result._data)
            return out_tensor
        return result
    _check_stacked(arr, g, "alltoall_single")
    arr = arr.reshape((n, n, -1) + tuple(arr.shape[2:]))
    out = _stacked(
        lambda x: jax.lax.all_to_all(x, g.axis_name, split_axis=1,
                                     concat_axis=0, tiled=True),
        g, arr, cache_key=("alltoall_single",))
    result = Tensor(out.reshape(_unwrap(in_tensor).shape))
    if out_tensor is not None:
        out_tensor._set_data(result._data)
        return out_tensor
    return result


def _ragged_alltoall_single(arr, in_tensor, out_tensor, in_split_sizes,
                            out_split_sizes, g: Group):
    """Ragged splits (reference's DCN EP path): every rank pads its send
    chunks to the GLOBAL max split (one tiny pmax exchange), rides the same
    tiled all_to_all, then slices its receive sizes back out."""
    n = g.nranks
    if len(in_split_sizes) != n or len(out_split_sizes) != n:
        raise ValueError("split size lists must have one entry per rank")
    if sum(in_split_sizes) != arr.shape[0]:
        raise ValueError(
            f"in_split_sizes sum {sum(in_split_sizes)} != dim0 "
            f"{arr.shape[0]}")
    local_max = max(list(in_split_sizes) + list(out_split_sizes) + [1])
    m = int(_stacked(lambda x: jax.lax.pmax(x, g.axis_name), g,
                     jnp.asarray([local_max], jnp.int32),
                     cache_key=("ragged_a2a_max",))[0])
    tail = tuple(arr.shape[1:])
    chunks = []
    off = 0
    for size in in_split_sizes:
        c = arr[off:off + size]
        if size < m:
            c = jnp.concatenate(
                [c, jnp.zeros((m - size,) + tail, arr.dtype)], axis=0)
        chunks.append(c)
        off += size
    packed = jnp.stack(chunks, axis=0)  # [n, m, ...]

    def body(x):
        return jax.lax.all_to_all(x[0], g.axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)[None]

    out = _stacked(body, g, packed, cache_key=("ragged_a2a", m))
    rows = out.reshape((n, m) + tail)
    parts = [rows[i, :out_split_sizes[i]] for i in range(n)]
    result = Tensor(jnp.concatenate(parts, axis=0) if parts
                    else jnp.zeros((0,) + tail, arr.dtype))
    if out_tensor is not None:
        out_tensor._set_data(result._data)
        return out_tensor
    return result


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Single controller: rank i's slot is in_object_list[i] (the src list
    is visible to all)."""
    g = group or _world()
    if _mp():
        import pickle
        src_idx = g.get_group_rank(src)
        if src_idx < 0:
            raise ValueError(
                f"scatter_object_list: src rank {src} not in group {g.ranks}")
        store, seq = _obj_store_and_seq(g)
        key = f"__objsc/{g.id}/{seq}"
        if g.rank == src_idx:
            if in_object_list is None or len(in_object_list) != g.nranks:
                raise ValueError(
                    "in_object_list must have one entry per rank")
            store.set(key, pickle.dumps(list(in_object_list)))
        out_object_list.append(pickle.loads(store.get(key))[g.rank])
        store.barrier(f"{key}/done", world_size=g.nranks)
        if g.rank == src_idx:
            store.delete_key(key)
        return out_object_list
    if in_object_list is None:
        raise ValueError("in_object_list required on the src rank")
    if len(in_object_list) != g.nranks:
        raise ValueError("in_object_list must have one entry per rank")
    out_object_list.append(in_object_list[g.rank_in_group])
    return out_object_list


def broadcast_object_list(object_list, src=0, group=None):
    """Multi-process: src's list replaces everyone's (src sets the store key
    once; the others fetch it). Single controller: identity."""
    g = group or _world()
    if _mp():
        import pickle
        src_idx = g.get_group_rank(src)
        if src_idx < 0:
            raise ValueError(
                f"broadcast_object_list: src rank {src} not in group "
                f"{g.ranks}")
        store, seq = _obj_store_and_seq(g)
        key = f"__objbc/{g.id}/{seq}"
        if g.rank == src_idx:
            store.set(key, pickle.dumps(list(object_list)))
        object_list[:] = pickle.loads(store.get(key))
        store.barrier(f"{key}/done", world_size=g.nranks)
        if g.rank == src_idx:
            store.delete_key(key)
    return object_list


class ReduceType:
    """auto-parallel reduce type enum (ref ReduceType for Partial)."""
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4


class ParallelMode:
    """fleet/base/topology.py:33 ParallelMode enum."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


def is_available():
    """paddle.distributed.is_available."""
    return True


def get_backend(group=None):
    """The communication backend name (XLA collectives over ICI/DCN)."""
    return "XCCL"


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """Host-side (gloo-analog) bootstrap: the TCPStore fills gloo's role
    (SURVEY §2.9 'host barriers via TCPStore')."""
    from ..core.native import TCPStore
    host, port = server_endpoint.rsplit(":", 1)
    is_master = rank_id == 0
    store = TCPStore(host, int(port), is_master=is_master,
                     world_size=rank_num)
    global _GLOO_STORE
    _GLOO_STORE = (store, rank_id, rank_num)


_GLOO_STORE = None
_GLOO_BARRIER_SEQ = [0]


def gloo_barrier():
    if _GLOO_STORE is None:
        raise RuntimeError("call gloo_init_parallel_env first")
    store, rank, n = _GLOO_STORE
    # per-call key: the store's done-flag is sticky, so a reused key would
    # let later barriers pass through without synchronizing
    _GLOO_BARRIER_SEQ[0] += 1
    store.barrier(f"gloo_barrier_{_GLOO_BARRIER_SEQ[0]}", n)


def gloo_release():
    global _GLOO_STORE
    _GLOO_STORE = None
