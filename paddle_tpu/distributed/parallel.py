"""Data parallelism.

Reference: paddle.DataParallel (python/paddle/distributed/parallel.py:202) +
EagerReducer grad bucketing (fluid/distributed/collective/reducer.h:88) with
backward-overlapped allreduce and the no_sync context.

TPU-native: with params replicated and the batch sharded over the dp axis,
GSPMD emits the gradient psum inside the compiled backward — bucketing,
reduce hooks, and comm/compute overlap are the XLA scheduler's job. The
wrapper here (1) places params, (2) shards inputs on dp, (3) keeps API parity
(no_sync, scale_loss)."""
from __future__ import annotations

import contextlib

from ..core.tensor import Tensor
from ..nn.layer import Layer
from .auto_parallel import ProcessMesh, Replicate, Shard, shard_tensor
from .collective import get_world_size, init_parallel_env


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        from .fleet.topology import get_hybrid_communicate_group
        hcg = get_hybrid_communicate_group()
        if hcg is not None:
            self._mesh = hcg.mesh
            self._dp_axis = "dp"
        else:
            g = group or init_parallel_env()
            self._mesh = g.mesh
            self._dp_axis = g.axis_name
        repl = [Replicate()] * len(self._mesh.dim_names)
        for p in layers.parameters():
            if p._dist_attr is None:
                shard_tensor(p, self._mesh, repl)

    def _shard_input(self, t):
        if isinstance(t, Tensor) and t.ndim > 0 and t._dist_attr is None:
            placements = [Shard(0) if n == self._dp_axis else Replicate()
                          for n in self._mesh.dim_names]
            if t.shape[0] % self._mesh.get_dim_size(self._dp_axis) == 0:
                return shard_tensor(t, self._mesh, placements)
        return t

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_input(i) for i in inputs)
        kwargs = {k: self._shard_input(v) for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        """Grad-accumulation guard. GSPMD defers the grad psum to whenever the
        grads are consumed, so accumulation without sync is the default; this
        context exists for API parity."""
        yield

    def scale_loss(self, loss):
        return loss

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


def sync_params_buffers(model, comm_group=None, src_rank=0,
                        is_model_parallel=False):
    """parallel.py:149 analog — single-controller params are already
    consistent; kept for API parity."""
    return None
