"""Semi-automatic parallel engine: Strategy / DistModel / to_static.

Reference: python/paddle/distributed/auto_parallel/api.py — ``Strategy``
(api.py:799: sharding/amp/pipeline/gradient_merge configs), ``DistModel``
(api.py:987: mode-switched train/eval/predict over the parallelized
program), ``to_static`` (api.py:1405), backed by the static ``Engine``
(auto_parallel/static/engine.py:61 — _build traces the program, _parallel
runs planner/partitioner/reshard, fit drives it).

TPU-native redesign: the planner/partitioner/reshard pipeline collapses into
GSPMD — parameters and inputs carry shardings (DistTensor = jax.Array with a
NamedSharding), jit.TrainStep stages forward+backward+update into one XLA
executable, and the compiler inserts the collectives the reference's
``Parallelizer``/``Reshard`` passes would have materialized. Strategy knobs
map onto TrainStep options (amp), optimizer-state sharding (ZeRO stages),
and gradient merge (accumulation windows).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer
from .auto_parallel import (ProcessMesh, Replicate, Shard, get_default_mesh,
                            shard_tensor)


class _Config:
    """Attribute bag with declared fields (DistributedStrategy-proto analog,
    framework/distributed_strategy.proto:359)."""

    _fields: Dict[str, Any] = {}

    def __init__(self, config: Optional[dict] = None):
        import copy
        for k, v in self._fields.items():
            # deep-copy mutable defaults so instances never share state
            setattr(self, k, copy.deepcopy(v))
        if config:
            for k, v in config.items():
                if k in self._fields:
                    setattr(self, k, v)

    def __repr__(self):
        inner = ", ".join(f"{k}={getattr(self, k)!r}" for k in self._fields)
        return f"{type(self).__name__}({inner})"


class _ShardingConfig(_Config):
    _fields = {"enable": False, "stage": 1, "degree": 8,
               "release_gradients": False}


class _AmpConfig(_Config):
    _fields = {"enable": False, "dtype": "bfloat16", "level": "O2",
               "init_loss_scaling": 32768.0, "use_master_grad": False,
               "custom_white_list": None, "custom_black_list": None}


class _PipelineConfig(_Config):
    _fields = {"enable": False, "schedule_mode": "1F1B",
               "micro_batch_size": 1, "accumulate_steps": 1, "vpp_degree": 1}


class _GradientMergeConfig(_Config):
    _fields = {"enable": False, "k_steps": 1, "avg": True}


class _RecomputeConfig(_Config):
    _fields = {"enable": False, "checkpoints": None, "refined_ops": None,
               "granularity": None}


class _FusedPassesConfig(_Config):
    _fields = {"enable": False, "fused_passes_list": []}


class Strategy:
    """paddle.distributed.Strategy (auto_parallel/api.py:799 analog)."""

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.sharding = _ShardingConfig(config.get("sharding"))
        self.amp = _AmpConfig(config.get("amp"))
        self.pipeline = _PipelineConfig(config.get("pipeline"))
        self.gradient_merge = _GradientMergeConfig(
            config.get("gradient_merge"))
        self.recompute = _RecomputeConfig(config.get("recompute"))
        self.fused_passes = _FusedPassesConfig(config.get("fused_passes"))

    def __repr__(self):
        return (f"Strategy(sharding={self.sharding}, amp={self.amp}, "
                f"pipeline={self.pipeline}, "
                f"gradient_merge={self.gradient_merge})")


class DistModel:
    """auto_parallel/api.py DistModel:987 analog.

    Wraps (layer, loss, optimizer, strategy) into compiled train/eval/
    predict steps. ``__call__`` dispatches on the current mode:

    - train():   one full fwd+bwd+update XLA executable (jit.TrainStep)
    - eval():    compiled fwd+loss
    - predict(): compiled fwd

    The reference reaches the same end through dy2static tracing + SPMD
    completion + partitioning + reshard + pass application; here the mesh
    shardings on parameters/inputs carry the same information and GSPMD
    materializes the communication.
    """

    def __init__(self, layer: Layer, loader=None, loss=None, optimizer=None,
                 strategy: Optional[Strategy] = None, metrics=None):
        self.network = layer
        self._loader = loader
        self._dist_loader = None
        if loader is not None:
            mesh = get_default_mesh()
            if mesh is not None:
                # shard the input pipeline over the mesh's data axis
                # (api.py:1792 shard_dataloader, wired as the reference's
                # Engine._prepare_dataloader does)
                from .auto_parallel import shard_dataloader
                try:
                    self._dist_loader = shard_dataloader(loader, mesh)
                except Exception:
                    self._dist_loader = loader
            else:
                self._dist_loader = loader
        self._loss = loss
        self._optimizer = optimizer
        self._strategy = strategy or Strategy()
        self._metrics = metrics or []
        self._mode = None
        self._train_step = None
        self._eval_fn = None
        self._predict_fn = None
        self._feed_names: List[str] = []
        self._acc_steps = 1
        if self._strategy.gradient_merge.enable:
            self._acc_steps = int(self._strategy.gradient_merge.k_steps)
        self._acc_count = 0

        self._apply_strategy()

        if optimizer is not None and loss is not None:
            self.train()
        elif loss is not None:
            self.eval()
        else:
            self.predict()

    # -- strategy application ------------------------------------------------
    def _apply_strategy(self):
        st = self._strategy
        mesh = get_default_mesh()
        if st.sharding.enable and self._optimizer is not None:
            from . import shard_optimizer
            # stage 1/2: optimizer-state (and, via GSPMD's reduce-scatter,
            # gradient) sharding over the mesh's leading axis
            shard_optimizer(self._optimizer, mesh)
            if st.sharding.stage >= 3 and mesh is not None:
                # stage 3 additionally shards the parameters themselves
                # (ZeRO-3): dim-0 Shard over the leading mesh axis where
                # divisible; XLA all-gathers them at use sites
                axis = mesh.dim_names[0]
                size = mesh.get_dim_size(axis)
                for p in self._optimizer._parameter_list:
                    if (p._dist_attr is None and p.ndim > 0
                            and p.shape[0] % size == 0):
                        place = [Shard(0) if n == axis else Replicate()
                                 for n in mesh.dim_names]
                        shard_tensor(p, mesh, place)
        if st.fused_passes.enable:
            # XLA owns operator fusion on TPU (the CINN/pass-zoo
            # disposition): say so instead of silently accepting config
            import warnings
            warnings.warn(
                "Strategy.fused_passes is absorbed by XLA's fusion "
                "pipeline on TPU; the listed passes "
                f"({st.fused_passes.fused_passes_list}) configure nothing")
        if st.recompute.enable:
            self._apply_recompute(st.recompute)
        self._amp_kwargs = None
        if st.amp.enable:
            self._amp_kwargs = {"enable": True, "dtype": st.amp.dtype,
                                "level": st.amp.level}
            if st.amp.custom_white_list:
                self._amp_kwargs["custom_white_list"] = (
                    st.amp.custom_white_list)
            if st.amp.custom_black_list:
                self._amp_kwargs["custom_black_list"] = (
                    st.amp.custom_black_list)

    def _apply_recompute(self, rc):
        """Strategy.recompute → real behavior (it used to parse and then
        silently do nothing).

        Models with a native recompute knob (config.use_recompute — the
        llama/gpt zoo) get it flipped (+ granularity when supported);
        otherwise each DIRECT sublayer (or just the ones named in
        `checkpoints`) becomes a recompute region via fleet.recompute —
        the reference's segment-at-checkpoints behavior at layer
        granularity."""
        import warnings

        net = self.network
        cfg = getattr(net, "config", None)
        if cfg is not None and hasattr(cfg, "use_recompute"):
            cfg.use_recompute = True
            if rc.checkpoints:
                warnings.warn(
                    "Strategy.recompute.checkpoints is ignored for models "
                    "with a native config.use_recompute knob (recompute "
                    "applies to every layer there)")
            if rc.granularity:
                if hasattr(cfg, "recompute_granularity"):
                    cfg.recompute_granularity = rc.granularity
                else:
                    warnings.warn(
                        f"model config has no recompute_granularity; "
                        f"'{rc.granularity}' dropped")
            return
        from ..core.tensor import Tensor
        from .fleet.recompute import recompute as _recompute

        def _wrap(sub):
            if getattr(sub, "_recompute_wrapped", False):
                return False
            orig = sub.forward
            # hint computed once: skips the per-call reflective closure
            # probe on the hot path (the pp_layers pattern)
            hint = any(not p.stop_gradient for p in sub.parameters())
            state = {"mode": None}

            def fwd(*a, **k):
                if state["mode"] == "rc":
                    return _recompute(orig, *a, _trainable_hint=hint, **k)
                # first call probes the output shape: fleet.recompute only
                # replays Tensor / list / tuple outputs — dict-returning
                # layers fall back (with one warning) instead of crashing
                out = orig(*a, **k)
                ok = isinstance(out, Tensor) or (
                    isinstance(out, (list, tuple))
                    and any(isinstance(o, Tensor) for o in out))
                if state["mode"] is None:
                    state["mode"] = "rc" if ok else "direct"
                    if not ok:
                        warnings.warn(
                            f"recompute skipped for {type(sub).__name__}: "
                            f"output type {type(out).__name__} is not "
                            f"replayable (Tensor/list/tuple only)")
                return out

            sub.forward = fwd
            sub._recompute_wrapped = True
            return True

        wrapped = 0
        if rc.checkpoints:
            names = list(rc.checkpoints)
            matched = set()
            all_named = dict(net.named_sublayers(include_self=False))
            # skip names nested under another matched name: wrapping both a
            # parent and its child would recompute the child twice
            hits = [n for n in names if n in all_named]
            hits = [n for n in hits
                    if not any(n != m and n.startswith(m + ".")
                               for m in hits)]
            for n in hits:
                if _wrap(all_named[n]):
                    matched.add(n)
                    wrapped += 1
            missing = [n for n in names if n not in all_named]
            if missing:
                warnings.warn(
                    f"Strategy.recompute.checkpoints entries not found in "
                    f"the model: {missing}")
        else:
            for _name, sub in net.named_children():
                wrapped += bool(_wrap(sub))
        if not wrapped:
            warnings.warn(
                "Strategy.recompute.enable had nothing to apply: the model "
                "has no config.use_recompute and no sublayers matched "
                "`checkpoints`")

    # -- modes ---------------------------------------------------------------
    def train(self):
        if self._loss is None or self._optimizer is None:
            raise ValueError("train mode needs both loss and optimizer")
        self._mode = "train"
        self.network.train()
        if self._train_step is None:
            from .. import jit

            def loss_fn(*batch):
                ins, lbls = self._split(batch)
                outs = self.network(*ins)
                outs = outs if isinstance(outs, (list, tuple)) else [outs]
                return self._loss(*outs, *lbls)

            self._train_step = jit.TrainStep(loss_fn, self._optimizer,
                                             amp=self._amp_kwargs)
        return self

    def eval(self):
        if self._loss is None:
            raise ValueError("eval mode needs a loss")
        self._mode = "eval"
        self.network.eval()
        if self._eval_fn is None:
            from .. import jit

            @jit.to_static
            def eval_fn(*batch):
                from ..autograd import no_grad
                with no_grad():
                    ins, lbls = self._split(batch)
                    outs = self.network(*ins)
                    outs = outs if isinstance(outs, (list, tuple)) else [outs]
                    return self._loss(*outs, *lbls)

            self._eval_fn = eval_fn
        return self

    def predict(self):
        self._mode = "predict"
        self.network.eval()
        if self._predict_fn is None:
            from .. import jit

            @jit.to_static
            def predict_fn(*batch):
                from ..autograd import no_grad
                with no_grad():
                    ins, _ = self._split(batch)
                    return self.network(*ins)

            self._predict_fn = predict_fn
        return self

    def _split(self, batch):
        batch = list(batch)
        if self._loss is None or self._mode == "predict":
            return batch, []
        if len(batch) < 2:
            raise ValueError(
                f"{self._mode} mode expects (inputs..., label); got "
                f"{len(batch)} tensor(s)")
        return batch[:-1], batch[-1:]

    def __call__(self, *args):
        import time as _time
        from ..observability.metrics import get_registry
        args = tuple(a if isinstance(a, Tensor) else Tensor(np.asarray(a))
                     for a in args)
        if self._mode not in ("train", "eval", "predict"):
            raise RuntimeError("mode not set; call train()/eval()/predict()")
        reg = get_registry()
        reg.counter("dist_steps_total", "DistModel steps by mode",
                    labelnames=("mode",)).labels(mode=self._mode).inc()
        t0 = _time.perf_counter()
        try:
            if self._mode == "train":
                if self._acc_steps > 1:
                    # gradient-merge: accumulate locally, step every k
                    # batches. (reference: gradient_merge pass wrapping the
                    # update in a conditional block — here the eager tape
                    # accumulates and the optimizer steps on the boundary)
                    return self._train_micro(args)
                return self._train_step(*args)
            if self._mode == "eval":
                return self._eval_fn(*args)
            return self._predict_fn(*args)
        finally:
            reg.histogram(
                "dist_step_seconds", "DistModel step wall time by mode",
                labelnames=("mode",)).labels(
                    mode=self._mode).observe(_time.perf_counter() - t0)

    def _train_micro(self, args):
        import contextlib
        ins, lbls = self._split(args)
        if self._amp_kwargs:
            from .. import amp as amp_mod
            ctx = amp_mod.auto_cast(**self._amp_kwargs)
        else:
            ctx = contextlib.nullcontext()
        with ctx:
            outs = self.network(*ins)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            loss = self._loss(*outs, *lbls)
        scaled = loss / self._acc_steps if self._strategy.gradient_merge.avg \
            else loss
        scaled.backward()
        self._acc_count += 1
        if self._acc_count >= self._acc_steps:
            self._optimizer.step()
            self._optimizer.clear_grad()
            self._acc_count = 0
        return loss

    # -- program/state introspection ----------------------------------------
    def state_dict(self, mode="all"):
        sd = {}
        if mode in ("all", "param", "params"):
            sd.update(self.network.state_dict())
        if mode in ("all", "opt") and self._optimizer is not None:
            for k, v in self._optimizer.state_dict().items():
                sd[f"optimizer.{k}"] = v
        return sd

    def set_state_dict(self, state_dict):
        net_sd = {k: v for k, v in state_dict.items()
                  if not k.startswith("optimizer.")}
        self.network.set_state_dict(net_sd)
        if self._optimizer is not None:
            opt_sd = {k[len("optimizer."):]: v for k, v in state_dict.items()
                      if k.startswith("optimizer.")}
            if opt_sd:
                self._optimizer.set_state_dict(opt_sd)

    def dist_loader(self):
        """The (mesh-sharded) input pipeline built from the ctor loader."""
        return self._dist_loader

    def dist_main_program(self, mode=None):
        """Reference returns the partitioned Program; the TPU analog is the
        jaxpr/compiled-executable entry of the active step (None before the
        first call compiles it)."""
        return self._train_step if (mode or self._mode) == "train" else (
            self._eval_fn if (mode or self._mode) == "eval"
            else self._predict_fn)


def to_static(layer: Layer, loader=None, loss=None, optimizer=None,
              strategy: Optional[Strategy] = None):
    """paddle.distributed.to_static (auto_parallel/api.py:1405 analog)."""
    return DistModel(layer, loader=loader, loss=loss, optimizer=optimizer,
                     strategy=strategy)
