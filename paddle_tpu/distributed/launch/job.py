"""Job / Pod / Container model.

Reference: distributed/launch/job/ — a Job is the whole distributed run, a
Pod is one node's set of processes, a Container wraps one spawned process
with env + log capture (launch/job/{job,pod,container}.py).
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Dict, List, Optional


class Container:
    """launch/job/container.py analog: one process + env + log file."""

    def __init__(self, entrypoint: List[str], env: Dict[str, str],
                 log_path: Optional[str] = None, rank: int = -1,
                 log_mode: str = "w"):
        self.entrypoint = entrypoint
        self.env = env
        self.log_path = log_path
        self.rank = rank
        self.log_mode = log_mode
        self.proc: Optional[subprocess.Popen] = None
        self._log_file = None

    def start(self):
        full_env = dict(os.environ)
        full_env.update(self.env)
        out = sys.stdout
        if self.log_path:
            log_dir = os.path.dirname(self.log_path)
            if log_dir:
                os.makedirs(log_dir, exist_ok=True)
            self._log_file = open(self.log_path, self.log_mode)
            out = self._log_file
        self.proc = subprocess.Popen(self.entrypoint, env=full_env,
                                     stdout=out, stderr=subprocess.STDOUT)
        return self

    @property
    def exit_code(self) -> Optional[int]:
        return None if self.proc is None else self.proc.poll()

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def terminate(self, timeout: float = 10.0):
        if self.proc is None:
            return
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
        if self._log_file:
            self._log_file.close()
            self._log_file = None

    def wait(self, timeout=None) -> Optional[int]:
        if self.proc is None:
            return None
        try:
            code = self.proc.wait(timeout=timeout)
            if self._log_file:
                self._log_file.close()
                self._log_file = None
            return code
        except subprocess.TimeoutExpired:
            return None

    def logs(self, tail: int = 200) -> str:
        if not self.log_path or not os.path.exists(self.log_path):
            return ""
        with open(self.log_path, "r", errors="replace") as f:
            return "".join(f.readlines()[-tail:])


class Pod:
    """launch/job/pod.py analog: the containers of one node."""

    def __init__(self, name: str = "pod"):
        self.name = name
        self.containers: List[Container] = []
        self.restarts = 0

    def add_container(self, container: Container):
        self.containers.append(container)

    def deploy(self):
        for c in self.containers:
            c.start()

    def is_running(self) -> bool:
        return any(c.alive() for c in self.containers)

    def failed_containers(self) -> List[Container]:
        return [c for c in self.containers
                if c.exit_code is not None and c.exit_code != 0]

    def exit_codes(self) -> List[Optional[int]]:
        return [c.exit_code for c in self.containers]

    def join(self, poll_interval: float = 1.0) -> int:
        """Wait for all containers; on any failure stop the rest and return
        the first non-zero code (controllers/collective.py watch loop)."""
        while True:
            failed = self.failed_containers()
            if failed:
                self.stop()
                return failed[0].exit_code
            if not self.is_running():
                for c in self.containers:  # reap + close log handles
                    c.wait(timeout=5)
                return 0
            time.sleep(poll_interval)

    def stop(self):
        for c in self.containers:
            c.terminate()


class Job:
    """launch/job/job.py analog."""

    def __init__(self, jid: str = "default", mode: str = "collective",
                 nnodes: int = 1):
        self.id = jid
        self.mode = mode
        self.nnodes = nnodes
        self.pods: List[Pod] = []
