"""python -m paddle_tpu.distributed.launch — the launcher CLI.

Reference: distributed/launch/main.py:20 launch() — parse env/args into a
context, pick a controller by mode, spawn per-rank processes with PADDLE_*
envs and per-rank logs.

TPU-native: one process per HOST (each drives its local chips through one
jax runtime); --nproc_per_node therefore defaults to 1, and multi-host jobs
pass --master (rank-0 KV) + --nnodes, with JAX coordination envs set for
jax.distributed.initialize inside the trainer.
"""
from __future__ import annotations

import argparse
import os
import sys

from .controllers import CollectiveController, KVServer


class Context:
    def __init__(self, args, script_args):
        self.nnodes = int(args.nnodes)
        self.nproc_per_node = int(args.nproc_per_node)
        self.node_rank = int(args.node_rank)
        self.world_size = self.nnodes * self.nproc_per_node
        self.master = args.master
        self.coordinator = args.master
        self.job_id = args.job_id
        self.log_dir = args.log_dir
        self.max_restarts = int(args.max_restarts)
        self.training_script_args = script_args


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="distributed launcher (launch/main.py:20 analog)")
    p.add_argument("--nnodes", default=os.environ.get("PADDLE_NNODES", "1"))
    p.add_argument("--nproc_per_node",
                   default=os.environ.get("PADDLE_NPROC_PER_NODE", "1"),
                   help="processes per host (1 per host drives all chips)")
    p.add_argument("--node_rank",
                   default=os.environ.get("PADDLE_NODE_RANK", "0"))
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER"),
                   help="rank-0 KV endpoint host:port (multi-host)")
    p.add_argument("--job_id", default="default")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--max_restarts", default="0",
                   help="restart budget on failure (elastic fault level)")
    p.add_argument("--run_mode", default="collective",
                   choices=["collective"])
    p.add_argument("script", help="training script")
    args, script_args = p.parse_known_args(argv)
    return args, [args.script] + script_args


def launch(argv=None) -> int:
    args, script_args = _parse(argv if argv is not None else sys.argv[1:])
    ctx = Context(args, script_args)
    server = None
    if ctx.nnodes == 1 and ctx.world_size > 1 and not ctx.master:
        # single-node multi-process: form a real world over loopback (the
        # reference's single-node multi-GPU launch does the same). The KV
        # master uses port p, the JAX coordinator p+1, the TCPStore p+2 —
        # probe all three before committing to a base port.
        import socket

        def _three_free_ports():
            for _ in range(32):
                socks = []
                try:
                    with socket.socket() as probe:
                        probe.bind(("127.0.0.1", 0))
                        base = probe.getsockname()[1]
                    for off in range(3):
                        s = socket.socket()
                        s.bind(("127.0.0.1", base + off))
                        socks.append(s)
                    return base
                except OSError:
                    continue
                finally:
                    for s in socks:
                        s.close()
            raise RuntimeError("no 3-consecutive-port window found")

        ctx.master = f"127.0.0.1:{_three_free_ports()}"
    if ctx.nnodes > 1 or (ctx.world_size > 1 and ctx.master):
        if not ctx.master:
            raise SystemExit(
                "--master host:port is required for multi-node jobs "
                "(rank 0 binds it; peers connect to it)")
        host, _, port = ctx.master.replace("http://", "").rpartition(":")
        if ctx.node_rank == 0:
            # rank 0 BINDS the advertised master port (HTTPMaster:73)
            server = KVServer(port=int(port)).start()
        ctx.master = f"http://{host}:{port}"
        # jax.distributed's gRPC coordination service needs its own bare
        # host:port, one above the KV port by convention (rank-0 trainer
        # binds it at initialize())
        ctx.coordinator = f"{host}:{int(port) + 1}"
    try:
        from ..fleet.elastic import enable_elastic, launch_elastic
        if enable_elastic(ctx):
            return launch_elastic(ctx)
        controller = CollectiveController(ctx).build_pod()
        return controller.run()
    finally:
        if server is not None:
            server.stop()


if __name__ == "__main__":
    sys.exit(launch())
