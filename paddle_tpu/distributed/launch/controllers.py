"""Launch controllers + rank-0 master KV.

Reference: distributed/launch/controllers/ — CollectiveController (spawns the
per-rank procs with PADDLE_* envs, watches them, restarts on failure up to a
limit), master.py HTTPMaster:73 (rank-0 key-value server for peer discovery)
/ ETCDMaster:186, watcher.py (peer failure detection),
CollectiveElasticController:254.

TPU-native notes: a TPU "rank" is a HOST (jax process), not a chip — one
process per host drives all its local chips, and JAX's own coordination
service (coordinator_address) plays the role the TCPStore plays in the
reference. The master KV here serves the launcher-level discovery/elastic
protocol over DCN, exactly like HTTPMaster.
"""
from __future__ import annotations

import http.server
import json
import os
import socket
import sys
import threading
import time
import urllib.request
from typing import Dict, List, Optional

from .job import Container, Job, Pod


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class KVServer:
    """HTTPMaster's KV store (launch/controllers/master.py:73 analog):
    PUT /kv/<key>, GET /kv/<key>, GET /kv  — rank-0 hosts it, peers register
    their endpoints under a job-scoped prefix."""

    def __init__(self, port: Optional[int] = None):
        self.port = port or _free_port()
        self._kv: Dict[str, str] = {}
        self._lock = threading.Lock()
        kv = self._kv
        lock = self._lock

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                val = self.rfile.read(n).decode()
                with lock:
                    kv[self.path.lstrip("/")] = val
                self.send_response(200)
                self.end_headers()

            def do_GET(self):
                key = self.path.lstrip("/")
                with lock:
                    if key == "":
                        body = json.dumps(kv).encode()
                    elif key in kv:
                        body = kv[key].encode()
                    else:
                        self.send_response(404)
                        self.end_headers()
                        return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_DELETE(self):
                with lock:
                    kv.pop(self.path.lstrip("/"), None)
                self.send_response(200)
                self.end_headers()

        self._server = http.server.ThreadingHTTPServer(("", self.port),
                                                       Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

    @property
    def endpoint(self):
        return f"http://127.0.0.1:{self.port}"


class KVClient:
    """Peer side of the master KV.

    Requests run under the shared resilience RetryPolicy: transient
    connection failures (a master mid-restart, an injected ``kv.request``
    chaos fault) back off and retry instead of killing the caller.
    HTTPError is a deliberate give-up (it IS a server answer — 404 has
    semantics here), and the ``kv.request`` fault point fires inside the
    retried body so chaos drills exercise the loop."""

    def __init__(self, endpoint: str, retry=None):
        self.endpoint = endpoint.rstrip("/")
        if retry is None:
            from ...resilience.retry import RetryPolicy
            retry = RetryPolicy(max_attempts=4, base_delay=0.05,
                                max_delay=1.0, deadline=10.0)
        if urllib.error.HTTPError not in retry.giveup:
            # the 404 -> None contract must hold under ANY policy: an HTTP
            # status is a server answer, never a transient to retry here
            import dataclasses
            retry = dataclasses.replace(
                retry,
                giveup=tuple(retry.giveup) + (urllib.error.HTTPError,))
        self.retry = retry

    def _open(self, req_or_url):
        from ...resilience.chaos import fault_point
        fault_point("kv.request")
        return urllib.request.urlopen(req_or_url, timeout=5)

    def put(self, key: str, value: str):
        req = urllib.request.Request(f"{self.endpoint}/{key}",
                                     data=value.encode(), method="PUT")
        self.retry.call(lambda: self._open(req).read(), point="kv.put")

    def get(self, key: str) -> Optional[str]:
        def fetch():
            with self._open(f"{self.endpoint}/{key}") as r:
                return r.read().decode()
        try:
            return self.retry.call(fetch, point="kv.get")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def delete(self, key: str):
        req = urllib.request.Request(f"{self.endpoint}/{key}",
                                     method="DELETE")
        self.retry.call(lambda: self._open(req).read(), point="kv.delete")

    def get_all(self) -> Dict[str, str]:
        def fetch():
            with self._open(self.endpoint + "/") as r:
                return json.loads(r.read().decode())
        return self.retry.call(fetch, point="kv.get_all")

    def wait(self, key: str, timeout: float = 60.0,
             interval: float = 0.5) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = self.get(key)
            if v is not None:
                return v
            time.sleep(interval)
        raise TimeoutError(f"KV key {key!r} not published within {timeout}s")


class Watcher:
    """launch/controllers/watcher.py analog: poll peer heartbeats in the KV
    and report missing peers."""

    def __init__(self, client: KVClient, my_rank: int, nnodes: int,
                 ttl: float = 30.0):
        self.client = client
        self.rank = my_rank
        self.nnodes = nnodes
        self.ttl = ttl

    def heartbeat(self):
        self.client.put(f"heartbeat/{self.rank}", str(time.time()))

    def dead_peers(self) -> List[int]:
        now = time.time()
        dead = []
        for r in range(self.nnodes):
            v = self.client.get(f"heartbeat/{r}")
            if v is None or now - float(v) > self.ttl:
                dead.append(r)
        return dead


def announce_restart(restarts: int, budget: int, code: int,
                     elastic: bool = False) -> None:
    """One format for the fault/elastic restart notice (both restart
    loops emit it; logs and tests grep for it)."""
    sys.stderr.write(
        f"restarting pod (attempt {restarts}/{budget}) after exit {code}"
        f"{' [elastic re-form]' if elastic else ''}\n")


class CollectiveController:
    """launch/controllers/collective.py analog: build the pod, deploy,
    watch, restart up to max_restarts (the reference's replicas/elastic
    levels)."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.pod = Pod()
        self.attempt = 0

    def build_pod(self):
        ctx = self.ctx
        n = ctx.nproc_per_node
        for local_rank in range(n):
            rank = ctx.node_rank * n + local_rank
            env = {
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_LOCAL_RANK": str(local_rank),
                "PADDLE_TRAINERS_NUM": str(ctx.world_size),
                "PADDLE_RANK_IN_NODE": str(local_rank),
                "PADDLE_MASTER": ctx.master or "",
                "PADDLE_JOB_ID": ctx.job_id,
                # pod incarnation: restarted ranks must not read a
                # previous attempt's control-plane records (e.g. the
                # collective watchdog's progress keys) as live peers
                "PADDLE_RESTART_ATTEMPT": str(self.attempt),
                # jax multi-host coordination (the TCPStore analog)
                "JAX_COORDINATOR_ADDRESS": ctx.coordinator or "",
                "JAX_PROCESS_ID": str(rank),
                "JAX_NUM_PROCESSES": str(ctx.world_size),
            }
            log_path = os.path.join(ctx.log_dir,
                                    f"workerlog.{rank}") if ctx.log_dir \
                else None
            self.pod.add_container(Container(
                entrypoint=[sys.executable] + ctx.training_script_args,
                env=env, log_path=log_path, rank=rank,
                # restart attempts APPEND so the failed attempt's evidence
                # (e.g. the watchdog's dead-peer report) survives into the
                # final logs; a fresh launch truncates stale files
                log_mode="w" if self.attempt == 0 else "a"))
        return self

    def _collate_logs(self):
        """Merge per-rank workerlogs into one rank-prefixed stream
        (the reference launcher's log aggregation; one file to read
        instead of N) — written as <log_dir>/collated.log."""
        ctx = self.ctx
        if not ctx.log_dir:
            return
        try:
            path = os.path.join(ctx.log_dir, "collated.log")
            with open(path, "w") as out:
                for c in sorted(self.pod.containers, key=lambda c: c.rank):
                    if not c.log_path or not os.path.exists(c.log_path):
                        continue
                    with open(c.log_path, errors="replace") as f:
                        for line in f:
                            out.write(f"[rank {c.rank}] {line}")
        except OSError:  # collation must never fail the job
            pass

    def run(self) -> int:
        ctx = self.ctx
        restarts = 0
        while True:
            self.pod.deploy()
            code = self.pod.join()
            self._collate_logs()
            if code == 0:
                return 0
            restarts += 1
            if restarts > ctx.max_restarts:
                if ctx.log_dir:
                    for c in self.pod.failed_containers():
                        sys.stderr.write(
                            f"---- rank {c.rank} (exit {c.exit_code}) "
                            f"last log ----\n{c.logs()}\n")
                return code
            announce_restart(restarts, ctx.max_restarts, code)
            self.pod = Pod()
            self.attempt = restarts
            self.build_pod()
