"""Launcher (reference: python/paddle/distributed/launch — SURVEY.md §2.12)."""
from .controllers import (CollectiveController, KVClient, KVServer, Watcher)
from .job import Container, Job, Pod
from .main import launch

__all__ = ["CollectiveController", "KVClient", "KVServer", "Watcher",
           "Container", "Job", "Pod", "launch"]
