"""paddle.distributed.rpc analog.

Reference: python/paddle/distributed/rpc + C++ fluid/distributed/rpc
(brpc-based send/recv of python callables). TPU-native: a lightweight
TCP/pickle RPC over the native TCPStore rendezvous (csrc/native.cc) — the
control plane the reference runs over brpc; tensor traffic belongs on
ICI/DCN collectives, not here.
"""
from __future__ import annotations

import os
import pickle
import socket
import socketserver
import threading
from concurrent.futures import Future
from typing import Any, Dict, Optional

from ..core.native import TCPStore

_state: Dict[str, Any] = {"store": None, "name": None, "rank": None,
                          "server": None, "peers": {}, "world_size": None}


class WorkerInfo:
    def __init__(self, name: str, rank: int, ip: str, port: int):
        self.name = name
        self.rank = rank
        self.ip = ip
        self.port = port

    def __repr__(self):
        return (f"WorkerInfo(name={self.name}, rank={self.rank}, "
                f"ip={self.ip}, port={self.port})")


class _RPCHandler(socketserver.StreamRequestHandler):
    def handle(self):
        try:
            req = pickle.load(self.rfile)
        except EOFError:
            return
        fn, args, kwargs = req
        try:
            result = ("ok", fn(*args, **kwargs))
        except Exception as e:  # noqa: BLE001 — marshalled to caller
            result = ("err", e)
        pickle.dump(result, self.wfile)
        self.wfile.flush()


class _RPCServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None):
    """rpc.init_rpc analog: rendezvous through the TCPStore at
    master_endpoint (default env PADDLE_MASTER_ENDPOINT / 127.0.0.1)."""
    rank = rank if rank is not None else int(os.environ.get(
        "PADDLE_TRAINER_ID", 0))
    world_size = world_size or int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER_ENDPOINT", "127.0.0.1:29650")
    host, port = endpoint.rsplit(":", 1)
    store = TCPStore(host, int(port), is_master=(rank == 0),
                     world_size=world_size, timeout=60.0)

    server = _RPCServer(("", 0), _RPCHandler)
    sport = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()

    my_ip = os.environ.get("PADDLE_LOCAL_IP")
    if my_ip is None:
        if host in ("127.0.0.1", "localhost"):
            my_ip = "127.0.0.1"
        else:
            # the address this host uses to reach the master — robust on
            # multi-NIC hosts and /etc/hosts loopback-mapped hostnames
            probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                probe.connect((host, int(port)))
                my_ip = probe.getsockname()[0]
            finally:
                probe.close()
    store.set(f"rpc/worker/{rank}",
              pickle.dumps(WorkerInfo(name, rank, my_ip, sport)))
    store.barrier("rpc_init", world_size=world_size)

    peers = {}
    for r in range(world_size):
        info: WorkerInfo = pickle.loads(store.get(f"rpc/worker/{r}"))
        peers[info.name] = info
        peers[r] = info
    _state.update(store=store, name=name, rank=rank, server=server,
                  peers=peers, world_size=world_size)


def get_worker_info(name: Optional[str] = None) -> WorkerInfo:
    if name is None:
        name = _state["name"]
    return _state["peers"][name]


def get_all_worker_infos():
    return [v for k, v in _state["peers"].items() if isinstance(k, int)]


def get_current_worker_info() -> WorkerInfo:
    return get_worker_info(_state["name"])


# Connection ESTABLISHMENT retries under the shared policy (a peer whose
# RPC server is still booting, or an injected transient fault); the
# payload exchange itself is NOT retried — an RPC body is not known to be
# idempotent, and replaying one on a flaky link could run it twice.
def _connect_retry():
    from ..resilience.retry import RetryPolicy
    return RetryPolicy(max_attempts=4, base_delay=0.05, max_delay=1.0,
                       deadline=10.0)


def _call(to, fn, args, kwargs, timeout):
    info = _state["peers"][to]
    with _connect_retry().call(
            socket.create_connection, (info.ip, info.port),
            timeout=timeout or None, point="rpc.connect") as s:
        wfile = s.makefile("wb")
        rfile = s.makefile("rb")
        pickle.dump((fn, args or (), kwargs or {}), wfile)
        wfile.flush()
        status, payload = pickle.load(rfile)
    if status == "err":
        raise payload
    return payload


def rpc_sync(to, fn, args=None, kwargs=None, timeout=None):
    """rpc.rpc_sync analog: run fn(*args, **kwargs) on worker `to`."""
    return _call(to, fn, args, kwargs, timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=None) -> Future:
    """rpc.rpc_async analog: returns a Future (``.wait()`` parity alias)."""
    fut: Future = Future()

    def runner():
        try:
            fut.set_result(_call(to, fn, args, kwargs, timeout))
        except Exception as e:  # noqa: BLE001
            fut.set_exception(e)

    threading.Thread(target=runner, daemon=True).start()
    fut.wait = lambda timeout=None: fut.result(timeout)  # type: ignore
    return fut


def shutdown(graceful: bool = True):
    """rpc.shutdown analog."""
    if graceful and _state.get("store") is not None:
        try:
            _state["store"].barrier("rpc_shutdown",
                                    world_size=_state["world_size"])
        except Exception:  # noqa: BLE001 — peers may already be gone
            pass
    server = _state.get("server")
    if server is not None:
        server.shutdown()
    store = _state.get("store")
    if store is not None:
        store.close()
    _state.update(store=None, name=None, rank=None, server=None, peers={},
                  world_size=None)


__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos",
           "get_current_worker_info", "WorkerInfo"]
