"""Collective desync watchdog over the TCPStore control plane.

Reference surface: comm_task_manager.cc's watchdog + the store-based
barrier timeout (tcp_store) — when a multi-process job hangs, the single
most valuable diagnostic is WHICH rank is stuck at WHICH collective while
its peers moved on (or entered a DIFFERENT collective — a mismatched
program). TPU-native: each rank publishes (seq, op, spec, ts) to the
job-wide TCPStore before entering a collective; a poller compares peers
and reports desyncs instead of letting the job die silently at the ICI
timeout.

Opt-in: ``enable_collective_watchdog(timeout=...)`` after
init_parallel_env in a multi-process world (no-op in single-controller
runs — GSPMD issues collectives from one program, so ranks cannot
diverge).
"""
from __future__ import annotations

import json
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["CollectiveWatchdog", "DesyncError",
           "enable_collective_watchdog", "disable_collective_watchdog",
           "get_watchdog", "reset_watchdog"]

_ACTIVE: List[Optional["CollectiveWatchdog"]] = [None]


class DesyncError(RuntimeError):
    pass


class CollectiveWatchdog:
    """Publishes this rank's collective progress; detects peer desync.

    enter(op, spec) before each collective; exit() after. A background
    poller flags:
      - MISMATCH: a peer at OUR seq entered a different collective OP —
        the ranks' programs diverged (the reference desync debugger's bug
        class). P2P pairs (send/recv) are legitimately asymmetric and
        exempt; tensor specs are diagnostic only (ragged alltoall ships
        different shapes per rank by design).
      - STUCK: this rank sat inside one collective > timeout while some
        peer is at a DIFFERENT position (ahead, behind, or missing — a
        dead rank shows up as a peer frozen at an older seq, the
        canonical hang).
      - SLOW: > timeout with every peer at the same position — reported
        for visibility but NOT poisoned (a genuinely big collective looks
        like this).
    Divergence reports poison later enter() calls with DesyncError so the
    hang surfaces as a python error instead of an ICI timeout.
    """

    # legitimately different op names across ranks of one exchange
    _ASYMMETRIC = frozenset({"send", "recv"})

    def __init__(self, store, rank: int, world_size: int,
                 timeout: float = 120.0, poll: Optional[float] = None,
                 on_desync: Optional[Callable[[dict], None]] = None,
                 prefix: str = "collective_wd", attempt: int = 0):
        self.store = store
        self.rank = rank
        self.world_size = world_size
        # pod incarnation (PADDLE_RESTART_ATTEMPT): published in every
        # record; peers whose records carry a DIFFERENT attempt are
        # excluded from desync decisions — an elastic restart must not
        # read the previous attempt's frozen seq as a live peer, and a
        # node whose restart count skews must not see its peers as
        # permanently missing (which a per-attempt key namespace would)
        self.attempt = attempt
        self.timeout = timeout
        self.poll = poll if poll is not None else max(1.0, timeout / 4)
        self.prefix = prefix
        self.on_desync = on_desync or self._default_report
        self._seq = 0
        self._inside = False
        self._enter_ts = 0.0
        self._enter_ts0 = 0.0
        self._cur = ("", "")
        self._poison: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._publish(done=True)

    # -- publishing ---------------------------------------------------------
    def _key(self, rank):
        return f"{self.prefix}/{rank}"

    def _publish(self, done: bool):
        rec = {"seq": self._seq, "op": self._cur[0], "spec": self._cur[1],
               "ts": time.time(), "done": done, "attempt": self.attempt}
        self.store.set(self._key(self.rank), json.dumps(rec))

    def enter(self, op: str, spec: str = ""):
        if self._poison is not None:
            raise DesyncError(
                f"collective desync detected earlier: {self._poison}")
        with self._lock:
            self._seq += 1
            self._cur = (op, spec)
            self._enter_ts = time.time()
            self._enter_ts0 = self._enter_ts  # never re-armed (see SLOW)
            self._inside = True
            self._publish(done=False)

    def exit(self):
        with self._lock:
            self._inside = False
            self._publish(done=True)

    def reset(self) -> Optional[dict]:
        """Clear the poisoned desync state after the application handled
        it (re-formed the group, restarted the straggler, ...). Without
        this, one report makes EVERY later enter() raise — the watchdog
        could flag but never participate in recovery. Returns the report
        it cleared (None if it wasn't poisoned) and republishes this
        rank's record as idle so peers don't read the stale in-collective
        entry as a hang."""
        with self._lock:
            report, self._poison = self._poison, None
            self._inside = False
            self._publish(done=True)
        return report

    @property
    def seq(self) -> int:
        """Collectives observed so far (public, for tests/metrics)."""
        return self._seq

    # -- detection ----------------------------------------------------------
    def _peer(self, rank) -> Optional[dict]:
        try:
            raw = self.store.get(self._key(rank), timeout=2.0)
        except Exception:
            return None
        try:
            return json.loads(raw.decode())
        except Exception:
            return None

    def check_once(self) -> Optional[dict]:
        """One desync scan; returns the report (also dispatched) or None."""
        with self._lock:
            inside = self._inside
            seq = self._seq
            cur = self._cur
            enter_ts = self._enter_ts
            enter_ts0 = self._enter_ts0
        if not inside:
            return None
        peers: Dict[int, dict] = {}
        missing: List[int] = []
        stale: List[int] = []
        for r in range(self.world_size):
            if r == self.rank:
                continue
            p = self._peer(r)
            if p is None:
                missing.append(r)
            elif p.get("attempt", 0) == self.attempt:
                peers[r] = p
            else:
                # records from another pod incarnation are benign WHILE
                # the peer could still be restarting (a lower attempt
                # means it has not republished yet; a higher one means WE
                # are the stale rank about to be replaced) — but a peer
                # that never republishes is dead, so past a generous
                # grace window it escalates like a missing rank
                stale.append(r)
        report = None
        if cur[0] not in self._ASYMMETRIC:
            for r, p in peers.items():
                if p["seq"] == seq and not p.get("done") \
                        and p["op"] != cur[0] \
                        and p["op"] not in self._ASYMMETRIC:
                    report = {"kind": "mismatch", "rank": self.rank,
                              "seq": seq, "op": cur[0], "spec": cur[1],
                              "peer": r, "peer_op": p["op"],
                              "peer_spec": p["spec"]}
                    break
        stuck_for = time.time() - enter_ts
        if report is None and stuck_for > self.timeout:
            ahead = {r: p["seq"] for r, p in peers.items()
                     if p["seq"] > seq}
            behind = {r: p["seq"] for r, p in peers.items()
                      if p["seq"] < seq or (p["seq"] == seq
                                            and p.get("done"))}
            base = {"rank": self.rank, "seq": seq, "op": cur[0],
                    "spec": cur[1], "stuck_for_s": round(stuck_for, 1)}
            if stale and time.time() - enter_ts0 > 3 * self.timeout:
                # restart-boot grace expired: an other-attempt record
                # that never refreshed is a dead rank, not a slow boot.
                # Measured from the UN-re-armed enter time (enter_ts0) —
                # the SLOW branch below re-arms enter_ts every ~timeout,
                # which would otherwise keep this horizon unreachable.
                missing = missing + stale
                base["peers_stale_attempt"] = stale
            if ahead or behind or missing:
                # a dead rank freezes at an older seq (behind) or loses
                # its store record (missing) — the canonical hang
                report = dict(base, kind="stuck", peers_ahead=ahead,
                              peers_behind=behind, peers_missing=missing)
            else:
                # everyone is inside the same collective: likely just a
                # big transfer — report for visibility, do NOT poison
                self.on_desync(dict(base, kind="slow"))
                with self._lock:
                    self._enter_ts = time.time()  # re-arm, don't spam
                return None
        if report is not None:
            # CC404: reset()/enter() read-and-clear _poison under _lock
            # from the app thread; this runs on the watchdog thread — a
            # bare write here can resurrect a report reset() just cleared.
            with self._lock:
                self._poison = report
            self.on_desync(report)
        return report

    def _default_report(self, report: dict):
        print(f"[collective-watchdog] DESYNC {json.dumps(report)}",
              file=sys.stderr, flush=True)

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self

        def loop():
            while not self._stop.wait(self.poll):
                try:
                    self.check_once()
                except Exception:
                    pass  # the watchdog must never take the job down

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="collective-watchdog")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def enable_collective_watchdog(timeout: float = 120.0,
                               poll: Optional[float] = None,
                               on_desync=None) -> Optional[CollectiveWatchdog]:
    """Arm the watchdog over the job's bootstrap store (multi-process
    worlds only; returns None — with a note — in single-controller runs)."""
    import os

    import jax

    from .collective import get_bootstrap_store
    store = get_bootstrap_store()
    if store is None or jax.process_count() <= 1:
        return None
    disable_collective_watchdog()  # re-arming must not leak a poller
    # pod incarnation: after an elastic pod restart the control-plane
    # store still holds the previous attempt's progress records; a
    # freshly restarted rank reading them would flag its (still booting)
    # peers as frozen at the old attempt's seq and abort the new pod
    attempt = int(os.environ.get("PADDLE_RESTART_ATTEMPT", "0") or 0)
    wd = CollectiveWatchdog(store, jax.process_index(), jax.process_count(),
                            timeout=timeout, poll=poll, on_desync=on_desync,
                            attempt=attempt)
    wd.start()
    _ACTIVE[0] = wd
    return wd


def disable_collective_watchdog():
    wd = _ACTIVE[0]
    if wd is not None:
        wd.stop()
        _ACTIVE[0] = None


def get_watchdog() -> Optional[CollectiveWatchdog]:
    return _ACTIVE[0]


def reset_watchdog() -> Optional[dict]:
    """reset() on the active watchdog (no-op, returning None, when none
    is armed) — the recovery path's counterpart to
    enable_collective_watchdog."""
    wd = _ACTIVE[0]
    return wd.reset() if wd is not None else None


def watch(op_name: str, tensor=None):
    """Context manager the collective entry points use: no-op unless a
    watchdog is armed."""
    wd = _ACTIVE[0]
    return _Watch(wd, op_name, tensor)


class _Watch:
    def __init__(self, wd, op_name, tensor):
        self.wd = wd
        self.op = op_name
        self.tensor = tensor

    def __enter__(self):
        if self.wd is not None:
            spec = ""
            t = self.tensor
            if t is not None and hasattr(t, "shape"):
                spec = f"{tuple(t.shape)}:{getattr(t, 'dtype', '')}"
            self.wd.enter(self.op, spec)
        return self

    def __exit__(self, *exc):
        if self.wd is not None:
            self.wd.exit()
        return False
