"""Sharded checkpoint save/load with reshard-on-load.

Reference: distributed/checkpoint/save_state_dict.py:104 (every rank writes
its local shards plus a coordinated Metadata) and load_state_dict.py:377 with
compute_overlap:247 — on load, each target shard fetches the overlapping
regions of whatever source chunks exist, so a checkpoint saved under one
parallel config loads under any other.

TPU-native redesign (single controller): a "rank's local shard" is a device
shard of a sharded jax.Array. Save walks `addressable_shards`, deduplicates
replicas, and writes one .npz per process plus metadata.pkl. Load runs the
same overlap algorithm region-wise: for every target device shard it copies
the intersecting slices out of the stored chunks, then assembles the global
array with jax.make_array_from_single_device_arrays — the full tensor is
never materialized on host, and resharding between arbitrary meshes falls
out of the overlap math (§2.19's converter semantics).
"""
from __future__ import annotations

import os
import pickle
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ...core.tensor import Tensor
from ...resilience.chaos import torn_write_bytes
from .metadata import (LocalTensorIndex, LocalTensorMetadata, Metadata,
                       TensorMetadata, chunk_crc)

_METADATA_FILE = "metadata.pkl"


def _atomic_write(final_path: str, data: bytes):
    """Crash-safe file publish: bytes land in a sibling temp file (through
    the ``checkpoint.write`` chaos point, so torn-write drills cut THERE)
    and only a complete temp file is renamed over the final name — a
    mid-write kill can no longer leave a corrupt file at the path a
    loader trusts."""
    tmp = final_path + ".tmp"
    torn_write_bytes(tmp, data, point="checkpoint.write")
    os.replace(tmp, final_path)


def _flatten(state_dict, prefix=""):
    flat = {}
    for k, v in state_dict.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(_flatten(v, prefix=f"{key}."))
        else:
            flat[key] = v
    return flat


def _unflatten_keys(state_dict):
    """Mapping flat-key -> (container, leaf-key) for in-place writes."""
    out = {}

    def walk(d, prefix=""):
        for k, v in d.items():
            key = f"{prefix}{k}"
            if isinstance(v, dict):
                walk(v, prefix=f"{key}.")
            else:
                out[key] = (d, k)

    walk(state_dict)
    return out


def _shard_index_to_offset(index, shape) -> Tuple[Tuple[int, ...], ...]:
    """jax shard .index (tuple of slices) -> (offset, local_shape)."""
    offset, local = [], []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        offset.append(start)
        local.append(stop - start)
    return tuple(offset), tuple(local)


def encode_stored_array(data: np.ndarray) -> np.ndarray:
    """ml_dtypes arrays (bf16/fp8) store as raw bits; the logical dtype
    rides the metadata. Identity for every numpy-native dtype."""
    if data.dtype.kind not in "fiub":
        return data.view(np.uint16 if data.dtype.itemsize == 2
                         else np.uint8)
    return data


def decode_stored_array(data: np.ndarray, stored_dtype) -> np.ndarray:
    """Undo ``encode_stored_array`` given the logical dtype."""
    if data.dtype != stored_dtype:
        return data.view(stored_dtype)
    return data


def pack_npz(arrays: Dict[str, np.ndarray]) -> bytes:
    """Serialize a chunk dict to npz bytes (one buffer, ready for an
    atomic/torn-write-instrumented publish)."""
    import io as _io
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def save_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, async_save: bool = False):
    """distributed.checkpoint.save_state_dict (save_state_dict.py:104)."""
    os.makedirs(path, exist_ok=True)
    flat = _flatten(state_dict)
    meta = Metadata()
    arrays = {}
    fname = f"data_{jax.process_index()}.npz"

    for key, value in flat.items():
        if not isinstance(value, Tensor):
            meta.extra_state[key] = value
            continue
        arr = value._data
        gshape = tuple(int(d) for d in arr.shape)
        tmeta = TensorMetadata(global_shape=gshape, dtype=str(arr.dtype))
        seen = set()
        for shard in arr.addressable_shards:
            offset, local = _shard_index_to_offset(shard.index, gshape)
            if offset in seen:
                continue  # replicas store once (reference dedups by rank)
            seen.add(offset)
            cid = Metadata.chunk_id(key, offset)
            data = encode_stored_array(np.asarray(shard.data))
            arrays[cid] = data
            tmeta.chunks.append(LocalTensorMetadata(
                global_offset=offset, local_shape=local,
                dtype=str(arr.dtype), checksum=chunk_crc(data)))
            meta.storage_metadata[cid] = fname
        meta.state_dict_metadata[key] = tmeta

    # each process writes its OWN metadata file; load merges the union, so
    # multi-host saves need no coordination and cannot clobber each other
    # (the reference instead gathers metadata at coordinator_rank)
    meta_name = f"metadata.{jax.process_index()}.pkl"

    def write():
        # data first, metadata last: a crash between the two leaves a
        # data file no metadata references — dead bytes, not corruption
        _atomic_write(os.path.join(path, fname), pack_npz(arrays))
        _atomic_write(os.path.join(path, meta_name), pickle.dumps(meta))

    if async_save:
        # device->host copies already happened above (np.asarray); only the
        # file IO rides the background thread (framework/io.py async_save:65
        # semantics — wait with wait_async_saves)
        from ...framework.io import _submit_async_save
        _submit_async_save(write)
    else:
        write()
    return meta


def _read_merged_metadata(path: str) -> Metadata:
    """Union of every process's metadata.{i}.pkl (and legacy metadata.pkl)."""
    import glob

    files = sorted(glob.glob(os.path.join(path, "metadata.*.pkl")))
    legacy = os.path.join(path, _METADATA_FILE)
    if os.path.exists(legacy):
        files.append(legacy)
    if not files:
        raise FileNotFoundError(f"no checkpoint metadata under {path!r}")
    merged = Metadata()
    for fn in files:
        with open(fn, "rb") as f:
            meta: Metadata = pickle.load(f)
        merged.extra_state.update(meta.extra_state)
        merged.storage_metadata.update(meta.storage_metadata)
        for key, tmeta in meta.state_dict_metadata.items():
            if key not in merged.state_dict_metadata:
                merged.state_dict_metadata[key] = tmeta
            else:
                have = {tuple(c.global_offset)
                        for c in merged.state_dict_metadata[key].chunks}
                for c in tmeta.chunks:
                    if tuple(c.global_offset) not in have:
                        merged.state_dict_metadata[key].chunks.append(c)
    return merged


def _overlap(dst_off, dst_shape, src_off, src_shape):
    """compute_overlap (load_state_dict.py:247 analog): per-dim intersection.
    Returns (dst_slices, src_slices) or None when disjoint."""
    dst_sl, src_sl = [], []
    for do, dn, so, sn in zip(dst_off, dst_shape, src_off, src_shape):
        lo = max(do, so)
        hi = min(do + dn, so + sn)
        if hi <= lo:
            return None
        dst_sl.append(slice(lo - do, hi - do))
        src_sl.append(slice(lo - so, hi - so))
    return tuple(dst_sl), tuple(src_sl)


class _ChunkReader:
    """Lazy per-file npz reader shared across keys."""

    def __init__(self, path, storage_metadata):
        self._path = path
        self._storage = storage_metadata
        self._files = {}

    def read(self, cid) -> np.ndarray:
        fname = self._storage[cid]
        if fname not in self._files:
            self._files[fname] = np.load(os.path.join(self._path, fname))
        return self._files[fname][cid]

    def close(self):
        for f in self._files.values():
            f.close()


def load_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0) -> None:
    """distributed.checkpoint.load_state_dict (load_state_dict.py:377):
    fills `state_dict`'s tensors IN PLACE, resharding stored chunks onto each
    tensor's current sharding."""
    meta = _read_merged_metadata(path)
    reader = _ChunkReader(path, meta.storage_metadata)
    writers = _unflatten_keys(state_dict)

    try:
        for key, (container, leaf) in writers.items():
            value = container[leaf]
            if not isinstance(value, Tensor):
                if key in meta.extra_state:
                    container[leaf] = meta.extra_state[key]
                continue
            if key not in meta.state_dict_metadata:
                raise KeyError(f"checkpoint at {path!r} has no tensor {key!r}")
            tmeta = meta.state_dict_metadata[key]
            gshape = tuple(int(d) for d in value._data.shape)
            if gshape != tuple(tmeta.global_shape):
                raise ValueError(
                    f"{key}: target global shape {gshape} != stored "
                    f"{tuple(tmeta.global_shape)}")
            value._set_data(_assemble(value._data, tmeta, key, reader))
    finally:
        reader.close()


def _np_dtype(name: str) -> np.dtype:
    """Logical dtype from metadata — ml_dtypes covers bf16/fp8 names."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


# public reuse surface: the elastic sharded checkpoint layer
# (resilience/sharded_checkpoint.py) and the mesh placement path
# (distributed/mesh.py::place_from_shards) run the SAME chunk math
shard_index_to_offset = _shard_index_to_offset
overlap_slices = _overlap
np_dtype = _np_dtype


def _assemble(target_arr, tmeta, key, reader):
    """Build a jax.Array matching target_arr's sharding from stored chunks."""
    gshape = tuple(int(d) for d in target_arr.shape)
    sharding = target_arr.sharding
    dtype = target_arr.dtype
    stored_dtype = _np_dtype(tmeta.dtype)
    locals_per_device = []
    for shard in target_arr.addressable_shards:
        dst_off, dst_shape = _shard_index_to_offset(shard.index, gshape)
        buf = np.empty(dst_shape, dtype=stored_dtype)
        filled = np.zeros(dst_shape, dtype=bool)
        for chunk in tmeta.chunks:
            ov = _overlap(dst_off, dst_shape, chunk.global_offset,
                          chunk.local_shape)
            if ov is None:
                continue
            dst_sl, src_sl = ov
            cid = Metadata.chunk_id(key, chunk.global_offset)
            data = reader.read(cid)
            if data.dtype != stored_dtype:  # raw-bit storage (bf16/fp8)
                data = data.view(stored_dtype)
            buf[dst_sl] = data[src_sl]
            filled[dst_sl] = True
        if not filled.all():
            raise ValueError(
                f"{key}: stored chunks do not cover the target shard at "
                f"offset {dst_off} (missing {int((~filled).sum())} elems)")
        locals_per_device.append(
            jax.device_put(buf.astype(dtype), shard.device))
    return jax.make_array_from_single_device_arrays(
        gshape, sharding, locals_per_device)
