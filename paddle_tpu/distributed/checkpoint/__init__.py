"""Distributed checkpoint (reference: python/paddle/distributed/checkpoint —
SURVEY.md §2.19): sharded save + overlap-resolving reshard-on-load."""
from .metadata import (LocalTensorIndex, LocalTensorMetadata, Metadata,
                       TensorMetadata)
from .save_load import load_state_dict, save_state_dict

__all__ = ["LocalTensorIndex", "LocalTensorMetadata", "Metadata",
           "TensorMetadata", "load_state_dict", "save_state_dict"]
