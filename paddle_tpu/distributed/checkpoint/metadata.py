"""Checkpoint metadata.

Reference: distributed/checkpoint/metadata.py:20-40 — LocalTensorMetadata
(global_offset + local_shape of one stored chunk), LocalTensorIndex, Metadata
(per-key chunk lists + storage mapping).
"""
from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


def chunk_crc(arr) -> int:
    """crc32 over a chunk's raw bytes — the ONE checksum definition the
    saver (save_load.py) and validator (resilience.checkpoint_manager)
    share."""
    import numpy as np
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


@dataclass
class LocalTensorMetadata:
    """One stored chunk of a tensor (metadata.py LocalTensorMetadata)."""
    global_offset: Tuple[int, ...]
    local_shape: Tuple[int, ...]
    dtype: str
    # crc32 of the stored bytes; None on checkpoints written before
    # checksums existed (loaders must getattr — old pickles restore
    # without this attribute at all)
    checksum: Optional[int] = None


@dataclass
class LocalTensorIndex:
    """Where a chunk lives (metadata.py LocalTensorIndex)."""
    tensor_key: str
    global_offset: Tuple[int, ...]


@dataclass
class TensorMetadata:
    global_shape: Tuple[int, ...]
    dtype: str
    chunks: List[LocalTensorMetadata] = field(default_factory=list)


@dataclass
class Metadata:
    """metadata.py Metadata analog: state-dict layout + chunk -> file map."""
    state_dict_metadata: Dict[str, TensorMetadata] = field(
        default_factory=dict)
    storage_metadata: Dict[str, str] = field(default_factory=dict)
    # non-tensor entries (python scalars, nested dict scaffolding)
    extra_state: Dict[str, object] = field(default_factory=dict)
    flat_mapping: Dict[str, object] = field(default_factory=dict)

    @staticmethod
    def chunk_id(key: str, global_offset) -> str:
        return f"{key}@{'_'.join(map(str, global_offset))}"
