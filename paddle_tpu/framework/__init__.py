from .io import load, save, async_save
