"""paddle.save / paddle.load analog (python/paddle/framework/io.py).

Pickle-based state-dict serialization with Tensors converted to numpy on save
and restored as Tensors on load. async_save (io.py:65 analog) snapshots to host
then writes on a background thread so the TPU isn't blocked on disk.
"""
from __future__ import annotations

import os
import pickle
import threading
from typing import Any

import numpy as np

from ..core.tensor import Tensor


def _to_storable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": obj.numpy(),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        seq = [_to_storable(v) for v in obj]
        return seq if isinstance(obj, list) else tuple(seq)
    return obj


def _from_storable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(obj["data"], stop_gradient=obj.get("stop_gradient", True),
                       name=obj.get("name"))
            return t
        return {k: _from_storable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        seq = [_from_storable(v, return_numpy) for v in obj]
        return seq if isinstance(obj, list) else tuple(seq)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_storable(obj), f, protocol=protocol)


import atexit

_ASYNC_THREADS = []
atexit.register(lambda: wait_async_saves())


def async_save(obj: Any, path: str, protocol: int = 4, sync_other_task=False,
               **configs):
    """Snapshot now, write in background (framework/io.py async_save:65)."""
    snapshot = _to_storable(obj)

    def _write():
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "wb") as f:
            pickle.dump(snapshot, f, protocol=protocol)

    return _submit_async_save(_write)


def wait_async_saves():
    while _ASYNC_THREADS:
        _ASYNC_THREADS.pop().join()


def load(path: str, return_numpy: bool = False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_storable(obj, return_numpy)


def _submit_async_save(write_fn):
    """Run a prepared writer on the async-save thread pool (shared with
    async_save; wait with wait_async_saves)."""
    t = threading.Thread(target=write_fn, daemon=True)
    t.start()
    _ASYNC_THREADS.append(t)
    return t
