"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new framework with the capabilities of the reference (PaddlePaddle,
see SURVEY.md): eager tensors + tape autograd, a functional op surface, nn
layers, optimizers, AMP, a compiled to_static path, and the distributed stack —
all built TPU-first on JAX/XLA/Pallas (compute) with native components for the
runtime tier. Public API names follow python/paddle/__init__.py so reference
users can migrate.
"""
from __future__ import annotations

# core
from .core.tensor import CPUPlace, Parameter, Place, Tensor, TPUPlace
from .core.dtype import (bfloat16, bool_, complex128, complex64, float16,
                         float32, float64, get_default_dtype, int16, int32,
                         int64, int8, set_default_dtype, uint8)
from .core.flags import get_flags, set_flags
from .core.random import seed
from .core.shims import (CUDAPinnedPlace, CUDAPlace, LazyGuard, XPUPlace,
                         batch, check_shape, create_parameter,
                         disable_signal_handler, dtype, finfo,
                         get_cuda_rng_state, get_rng_state, iinfo,
                         set_cuda_rng_state, set_printoptions, set_rng_state)

# paddle.bool is the dtype (shadows builtins inside this namespace only,
# matching python/paddle/__init__.py)
bool = bool_

# autograd
from .autograd import (PyLayer, PyLayerContext, enable_grad, grad,
                       is_grad_enabled, no_grad, set_grad_enabled)

# ops — flat namespace like paddle.*
from .ops import *  # noqa: F401,F403
from .ops import (abs, all, any, max, min, pow, round, sum)  # noqa: F401

# subpackages
from . import amp
from . import audio
from . import autograd
from . import device
from . import distributed
from . import distribution
from . import fft
from . import framework
from . import hapi
from . import signal
# `from .ops import *` above bound paddle_tpu.linalg to ops.linalg (wildcard
# re-exports submodule names); force the real namespace package over it
import importlib as _importlib
linalg = _importlib.import_module(__name__ + ".linalg")
from . import incubate
from . import io
from . import jit
from . import metric
from . import nn
from . import optimizer
from . import profiler
from . import observability
from . import perf
from . import resilience
from . import geometric
from . import hub
from . import inference
from . import onnx
from . import text
from . import quantization
from . import sparse
from . import utils
from . import vision
from . import static
from . import analysis  # registers the DF* diagnostic passes in static.ir
from .hapi import Model, callbacks, summary
from .distributed.parallel import DataParallel
from .framework.io import async_save, load, save
from .nn.layer import ParamAttr
from .utils.flops import flops
from .nn import functional as _F

# paddle.disable_static/enable_static are no-ops here (eager is the default;
# the compiled path is paddle_tpu.jit)
def disable_static(place=None):
    return None


def enable_static():
    return None


def in_dynamic_mode():
    return True


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_tpu():
    return True


def device_count():
    import jax
    return len(jax.devices())


def get_device():
    import jax
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def set_device(device):
    return device


def synchronize():
    """Block until all dispatched work completes (device sync)."""
    import jax
    (jax.device_put(0) + 0).block_until_ready()


__version__ = "0.1.0"
