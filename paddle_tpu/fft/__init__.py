"""paddle.fft namespace.

Reference: python/paddle/fft.py (fft_c2c/r2c/c2r kernels under
phi/kernels/funcs/fft.cc). Here each transform is one XLA fft HLO emitted
through the op registry, so it records on the autograd tape like any op.

Norm convention matches the reference: "backward" (scale on inverse),
"forward" (scale on forward), "ortho" (sqrt split).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ops.registry import defop


def _norm(norm):
    if norm not in ("backward", "forward", "ortho"):
        raise ValueError(f"unsupported norm: {norm}")
    return norm


@defop(name="fft_c2c")
def _fft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.fft(x, n=n, axis=axis, norm=_norm(norm))


@defop(name="ifft_c2c")
def _ifft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=_norm(norm))


@defop(name="fft_r2c")
def _rfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=_norm(norm))


@defop(name="fft_c2r")
def _irfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=_norm(norm))


@defop(name="hfft_op")
def _hfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.hfft(x, n=n, axis=axis, norm=_norm(norm))


@defop(name="ihfft_op")
def _ihfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ihfft(x, n=n, axis=axis, norm=_norm(norm))


@defop(name="fft2_op")
def _fft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.fft2(x, s=s, axes=tuple(axes), norm=_norm(norm))


@defop(name="ifft2_op")
def _ifft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.ifft2(x, s=s, axes=tuple(axes), norm=_norm(norm))


@defop(name="rfft2_op")
def _rfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.rfft2(x, s=s, axes=tuple(axes), norm=_norm(norm))


@defop(name="irfft2_op")
def _irfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.irfft2(x, s=s, axes=tuple(axes), norm=_norm(norm))


@defop(name="fftn_op")
def _fftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=_norm(norm))


@defop(name="ifftn_op")
def _ifftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=_norm(norm))


@defop(name="rfftn_op")
def _rfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.rfftn(x, s=s, axes=axes, norm=_norm(norm))


@defop(name="irfftn_op")
def _irfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=_norm(norm))


@defop(name="fftshift_op")
def _fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


@defop(name="ifftshift_op")
def _ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)


def fft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft(x, n=n, axis=axis, norm=norm)


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return _ifft(x, n=n, axis=axis, norm=norm)


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return _rfft(x, n=n, axis=axis, norm=norm)


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return _irfft(x, n=n, axis=axis, norm=norm)


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return _hfft(x, n=n, axis=axis, norm=norm)


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return _ihfft(x, n=n, axis=axis, norm=norm)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _fft2(x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _ifft2(x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _rfft2(x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _irfft2(x, s=s, axes=axes, norm=norm)


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return _fftn(x, s=s, axes=axes, norm=norm)


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return _ifftn(x, s=s, axes=axes, norm=norm)


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return _rfftn(x, s=s, axes=axes, norm=norm)


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return _irfftn(x, s=s, axes=axes, norm=norm)


def fftshift(x, axes=None, name=None):
    return _fftshift(x, axes=axes)


def ifftshift(x, axes=None, name=None):
    return _ifftshift(x, axes=axes)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from ..core.tensor import Tensor
    out = jnp.fft.fftfreq(n, d=d)
    if dtype is not None:
        out = out.astype(str(dtype).replace("paddle.", ""))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from ..core.tensor import Tensor
    out = jnp.fft.rfftfreq(n, d=d)
    if dtype is not None:
        out = out.astype(str(dtype).replace("paddle.", ""))
    return Tensor(out)


__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
           "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn", "fftshift",
           "ifftshift", "fftfreq", "rfftfreq"]


# -- hermitian 2-D / N-D variants (ref paddle.fft.hfft2/ihfft2/hfftn/ihfftn:
#    hermitian FFT = real spectrum of a hermitian-symmetric signal; composed
#    from the 1-D hermitian transform over the last axis + complex FFTs over
#    the leading axes, matching numpy's definition)
@defop()
def _hfftn(x, s=None, axes=None, norm="backward"):
    if axes is None:
        axes = tuple(range(x.ndim))
    axes = tuple(a % x.ndim for a in axes)
    sizes = list(s) if s is not None else [None] * len(axes)
    for a, n_ in zip(axes[:-1], sizes[:-1]):
        x = jnp.fft.fft(x, n=n_, axis=a, norm=_norm(norm))
    return jnp.fft.hfft(x, n=sizes[-1], axis=axes[-1], norm=_norm(norm))


@defop()
def _ihfftn(x, s=None, axes=None, norm="backward"):
    if axes is None:
        axes = tuple(range(x.ndim))
    axes = tuple(a % x.ndim for a in axes)
    sizes = list(s) if s is not None else [None] * len(axes)
    out = jnp.fft.ihfft(x, n=sizes[-1], axis=axes[-1], norm=_norm(norm))
    for a, n_ in zip(axes[:-1], sizes[:-1]):
        out = jnp.fft.ifft(out, n=n_, axis=a, norm=_norm(norm))
    return out


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _ihfftn(x, s=s, axes=axes, norm=norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return _hfftn(x, s=s, axes=axes, norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return _ihfftn(x, s=s, axes=axes, norm=norm)


__all__ += ["hfft2", "ihfft2", "hfftn", "ihfftn"]
