"""ResNet family (BASELINE.md config #1; reference
python/paddle/vision/models/resnet.py — same block/arch structure, rebuilt on
the XLA conv path where convs lower to single conv_general_dilated HLOs).
"""
from __future__ import annotations

from ..nn.layer import Layer
from ..nn import functional as F
from ..nn.common import Linear
from ..nn.conv import Conv2D
from ..nn.norm import BatchNorm2D
from ..nn.pooling import AdaptiveAvgPool2D, MaxPool2D


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(planes)
        self.conv2 = Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = BatchNorm2D(planes)
        self.downsample = downsample
        if downsample is not None:
            self.add_sublayer("downsample", downsample)

    def forward(self, x):
        identity = x
        out = F.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return F.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64):
        super().__init__()
        # resnext/wide_resnet widen the 3x3 stage (vision/models/resnet.py
        # BottleneckBlock width arithmetic)
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = BatchNorm2D(width)
        self.conv2 = Conv2D(width, width, 3, stride=stride, padding=1,
                            groups=groups, bias_attr=False)
        self.bn2 = BatchNorm2D(width)
        self.conv3 = Conv2D(width, planes * self.expansion, 1,
                            bias_attr=False)
        self.bn3 = BatchNorm2D(planes * self.expansion)
        self.downsample = downsample
        if downsample is not None:
            self.add_sublayer("downsample", downsample)

    def forward(self, x):
        identity = x
        out = F.relu(self.bn1(self.conv1(x)))
        out = F.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return F.relu(out + identity)


class _Downsample(Layer):
    def __init__(self, inplanes, outplanes, stride):
        super().__init__()
        self.conv = Conv2D(inplanes, outplanes, 1, stride=stride,
                           bias_attr=False)
        self.bn = BatchNorm2D(outplanes)

    def forward(self, x):
        return self.bn(self.conv(x))


class _Sequential(Layer):
    def __init__(self, blocks):
        super().__init__()
        self.blocks = blocks
        for i, b in enumerate(blocks):
            self.add_sublayer(str(i), b)

    def forward(self, x):
        for b in self.blocks:
            x = b(x)
        return x


class ResNet(Layer):
    """vision/models/resnet.py:ResNet analog. Input NCHW."""

    def __init__(self, block, depth_layers, num_classes=1000,
                 with_pool=True, groups=1, width_per_group=64):
        super().__init__()
        if (groups != 1 or width_per_group != 64) and \
                not issubclass(block, BottleneckBlock):
            raise ValueError(
                "groups/width_per_group only apply to BottleneckBlock "
                "ResNets (resnext/wide variants)")
        self.inplanes = 64
        self.groups = groups
        self.base_width = width_per_group
        self.conv1 = Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False)
        self.bn1 = BatchNorm2D(64)
        self.maxpool = MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, depth_layers[0])
        self.layer2 = self._make_layer(block, 128, depth_layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, depth_layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, depth_layers[3], stride=2)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = _Downsample(self.inplanes, planes * block.expansion,
                                     stride)
        extra = ({"groups": self.groups, "base_width": self.base_width}
                 if issubclass(block, BottleneckBlock) else {})
        layers = [block(self.inplanes, planes, stride, downsample, **extra)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, **extra))
        return _Sequential(layers)

    def forward(self, x):
        x = F.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def _load_pretrained(model, arch):
    from ..utils.checkpoint_converter import load_pretrained
    load_pretrained(model, arch)
    return model


def resnet18(pretrained=False, **kwargs):
    model = ResNet(BasicBlock, [2, 2, 2, 2], **kwargs)
    return _load_pretrained(model, "resnet18") if pretrained else model


def resnet34(pretrained=False, **kwargs):
    model = ResNet(BasicBlock, [3, 4, 6, 3], **kwargs)
    return _load_pretrained(model, "resnet34") if pretrained else model


def resnet50(pretrained=False, **kwargs):
    model = ResNet(BottleneckBlock, [3, 4, 6, 3], **kwargs)
    return _load_pretrained(model, "resnet50") if pretrained else model


def resnet101(pretrained=False, **kwargs):
    model = ResNet(BottleneckBlock, [3, 4, 23, 3], **kwargs)
    return _load_pretrained(model, "resnet101") if pretrained else model


def resnet152(pretrained=False, **kwargs):
    model = ResNet(BottleneckBlock, [3, 8, 36, 3], **kwargs)
    return _load_pretrained(model, "resnet152") if pretrained else model


def resnext50_32x4d(pretrained=False, **kwargs):
    model = ResNet(BottleneckBlock, [3, 4, 6, 3], groups=32,
                  width_per_group=4, **kwargs)
    return _load_pretrained(model, "resnext50_32x4d") if pretrained else model


def resnext50_64x4d(pretrained=False, **kwargs):
    model = ResNet(BottleneckBlock, [3, 4, 6, 3], groups=64,
                  width_per_group=4, **kwargs)
    return _load_pretrained(model, "resnext50_64x4d") if pretrained else model


def resnext101_32x4d(pretrained=False, **kwargs):
    model = ResNet(BottleneckBlock, [3, 4, 23, 3], groups=32,
                  width_per_group=4, **kwargs)
    return _load_pretrained(model, "resnext101_32x4d") if pretrained else model


def resnext101_64x4d(pretrained=False, **kwargs):
    model = ResNet(BottleneckBlock, [3, 4, 23, 3], groups=64,
                  width_per_group=4, **kwargs)
    return _load_pretrained(model, "resnext101_64x4d") if pretrained else model


def resnext152_32x4d(pretrained=False, **kwargs):
    model = ResNet(BottleneckBlock, [3, 8, 36, 3], groups=32,
                  width_per_group=4, **kwargs)
    return _load_pretrained(model, "resnext152_32x4d") if pretrained else model


def resnext152_64x4d(pretrained=False, **kwargs):
    model = ResNet(BottleneckBlock, [3, 8, 36, 3], groups=64,
                  width_per_group=4, **kwargs)
    return _load_pretrained(model, "resnext152_64x4d") if pretrained else model


def wide_resnet50_2(pretrained=False, **kwargs):
    model = ResNet(BottleneckBlock, [3, 4, 6, 3], width_per_group=128,
                  **kwargs)
    return _load_pretrained(model, "wide_resnet50_2") if pretrained else model


def wide_resnet101_2(pretrained=False, **kwargs):
    model = ResNet(BottleneckBlock, [3, 4, 23, 3], width_per_group=128,
                  **kwargs)
    return _load_pretrained(model, "wide_resnet101_2") if pretrained else model
