"""Diffusion UNet model family (the Stable-Diffusion kernel mix).

Reference surface: BASELINE.md config 5 — the reference benchmarks an
SD-style UNet (conv + GroupNorm/SiLU + self/cross attention) as an
external-model config; paddle serves it through the same nn.Conv2D /
GroupNorm / attention ops this file composes. TPU-first: NCHW convs XLA
lays out for the MXU, GroupNorm/SiLU fused by XLA, attention through the
shared scaled_dot_product_attention path (flash kernel when eligible),
static shapes throughout so one compile serves every step.

Pieces:
- timestep_embedding: sinusoidal features -> 2-layer MLP (DDPM/SD form)
- ResBlock: GroupNorm/SiLU conv pair + time-emb injection + skip
- TransformerBlock: self-attn, optional cross-attn over a context
  sequence (text conditioning), gelu MLP — the SD "spatial transformer"
- UNetModel: down path with skips, attended middle, up path, out conv
- ddpm_loss / ddim_sample: the training objective and a deterministic
  sampler so the family is usable end to end
"""
from __future__ import annotations

import functools
import math
from dataclasses import dataclass, replace
from typing import List, Optional, Sequence

import numpy as np

from .. import nn
from ..nn import functional as F
from ..nn.layer import Layer

__all__ = ["UNetConfig", "UNetModel", "ddpm_loss", "ddim_sample",
           "unet_tiny_config", "sd_unet_config"]


@dataclass
class UNetConfig:
    in_channels: int = 3
    out_channels: int = 3
    base_channels: int = 64
    channel_mults: Sequence[int] = (1, 2, 4)
    num_res_blocks: int = 2
    attn_levels: Sequence[int] = (1, 2)   # indices into channel_mults
    num_heads: int = 4
    context_dim: Optional[int] = None     # cross-attention width (None = off)
    groups: int = 8
    dtype: str = "float32"


def unet_tiny_config(**over) -> UNetConfig:
    cfg = UNetConfig(base_channels=32, channel_mults=(1, 2),
                     num_res_blocks=1, attn_levels=(1,), num_heads=2,
                     groups=4)
    # dataclasses.replace rejects unknown fields — a typo'd kwarg errors
    # instead of silently building the default architecture
    return replace(cfg, **over)


def sd_unet_config(**over) -> UNetConfig:
    """SD-1.x-shaped config (4-ch latents, 320 base, cross-attn 768)."""
    cfg = UNetConfig(in_channels=4, out_channels=4, base_channels=320,
                     channel_mults=(1, 2, 4, 4), num_res_blocks=2,
                     attn_levels=(0, 1, 2), num_heads=8, context_dim=768,
                     groups=32)
    return replace(cfg, **over)


@functools.lru_cache(maxsize=8)
def _freqs_table(half: int, max_period: float):
    """Device-resident sinusoid frequencies (built once per (dim, period),
    not per forward)."""
    import paddle_tpu as paddle
    return paddle.to_tensor(
        np.exp(-math.log(max_period) * np.arange(half, dtype=np.float32)
               / half))


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """Sinusoidal timestep features [B, dim] (DDPM §3.3 / SD form)."""
    import paddle_tpu as paddle
    half = dim // 2
    freqs = _freqs_table(half, max_period)
    ang = t.astype("float32").unsqueeze(-1) * freqs.unsqueeze(0)
    emb = paddle.concat([paddle.cos(ang), paddle.sin(ang)], axis=-1)
    if dim % 2:
        emb = paddle.concat([emb, paddle.zeros([emb.shape[0], 1])], axis=-1)
    return emb


class ResBlock(Layer):
    """GroupNorm/SiLU conv pair with time-embedding injection."""

    def __init__(self, cfg: UNetConfig, ch_in: int, ch_out: int,
                 temb_dim: int):
        super().__init__(dtype=cfg.dtype)
        g = min(cfg.groups, ch_in)
        self.n1 = nn.GroupNorm(g, ch_in)
        self.c1 = nn.Conv2D(ch_in, ch_out, 3, padding=1)
        self.temb = nn.Linear(temb_dim, ch_out)
        self.n2 = nn.GroupNorm(min(cfg.groups, ch_out), ch_out)
        self.c2 = nn.Conv2D(ch_out, ch_out, 3, padding=1)
        self.skip = (nn.Conv2D(ch_in, ch_out, 1) if ch_in != ch_out
                     else None)

    def forward(self, x, temb):
        h = self.c1(F.silu(self.n1(x)))
        h = h + self.temb(F.silu(temb)).unsqueeze(-1).unsqueeze(-1)
        h = self.c2(F.silu(self.n2(h)))
        return (x if self.skip is None else self.skip(x)) + h


class TransformerBlock(Layer):
    """SD spatial transformer: self-attn (+ optional cross-attn over a
    context sequence) + gelu MLP over the flattened spatial tokens."""

    def __init__(self, cfg: UNetConfig, ch: int):
        super().__init__(dtype=cfg.dtype)
        self.ch = ch
        self.heads = cfg.num_heads
        self.norm = nn.GroupNorm(min(cfg.groups, ch), ch)
        self.ln1 = nn.LayerNorm(ch)
        self.to_qkv = nn.Linear(ch, 3 * ch, bias_attr=False)
        self.proj1 = nn.Linear(ch, ch)
        self.cross = cfg.context_dim is not None
        if self.cross:
            self.ln_x = nn.LayerNorm(ch)
            self.to_q = nn.Linear(ch, ch, bias_attr=False)
            self.to_kv = nn.Linear(cfg.context_dim, 2 * ch, bias_attr=False)
            self.proj_x = nn.Linear(ch, ch)
        self.ln2 = nn.LayerNorm(ch)
        self.mlp1 = nn.Linear(ch, 4 * ch)
        self.mlp2 = nn.Linear(4 * ch, ch)

    def _attn(self, q, k, v):
        """[B, T, ch] x [B, S, ch] heads-split sdpa (flash when eligible)."""
        b, t, _ = q.shape
        s = k.shape[1]
        hd = self.ch // self.heads
        q = q.reshape([b, t, self.heads, hd])
        k = k.reshape([b, s, self.heads, hd])
        v = v.reshape([b, s, self.heads, hd])
        out = F.scaled_dot_product_attention(q, k, v)
        return out.reshape([b, t, self.ch])

    def forward(self, x, context=None):
        b, c, hh, ww = x.shape
        tokens = self.norm(x).reshape([b, c, hh * ww]).transpose([0, 2, 1])
        t1 = self.ln1(tokens)
        qkv = self.to_qkv(t1).reshape([b, hh * ww, 3, c])
        tokens = tokens + self.proj1(
            self._attn(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]))
        if self.cross and context is not None:
            tx = self.ln_x(tokens)
            kv = self.to_kv(context)
            tokens = tokens + self.proj_x(self._attn(
                self.to_q(tx), kv[:, :, :c], kv[:, :, c:]))
        t2 = self.ln2(tokens)
        tokens = tokens + self.mlp2(F.gelu(self.mlp1(t2)))
        return x + tokens.transpose([0, 2, 1]).reshape([b, c, hh, ww])


class UNetModel(Layer):
    """Time-conditioned UNet with skip connections (the SD denoiser
    shape). forward(x [B, C, H, W], t [B], context [B, S, ctx]) ->
    predicted noise [B, out_channels, H, W]."""

    def __init__(self, config: UNetConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        ch0 = config.base_channels
        temb = 4 * ch0
        self.temb_dim = ch0
        self.t1 = nn.Linear(ch0, temb)
        self.t2 = nn.Linear(temb, temb)
        self.inc = nn.Conv2D(config.in_channels, ch0, 3, padding=1)

        downs: List[Layer] = []
        skips = [ch0]
        ch = ch0
        for li, mult in enumerate(config.channel_mults):
            out = ch0 * mult
            for _ in range(config.num_res_blocks):
                blk = [ResBlock(config, ch, out, temb)]
                if li in config.attn_levels:
                    blk.append(TransformerBlock(config, out))
                downs.append(nn.LayerList(blk))
                ch = out
                skips.append(ch)
            if li != len(config.channel_mults) - 1:
                downs.append(nn.Conv2D(ch, ch, 3, stride=2, padding=1))
                skips.append(ch)
        self.downs = nn.LayerList(downs)

        self.mid1 = ResBlock(config, ch, ch, temb)
        self.mid_attn = TransformerBlock(config, ch)
        self.mid2 = ResBlock(config, ch, ch, temb)

        ups: List[Layer] = []
        for li, mult in reversed(tuple(enumerate(config.channel_mults))):
            out = ch0 * mult
            for _ in range(config.num_res_blocks + 1):
                blk = [ResBlock(config, ch + skips.pop(), out, temb)]
                if li in config.attn_levels:
                    blk.append(TransformerBlock(config, out))
                ups.append(nn.LayerList(blk))
                ch = out
            if li != 0:
                ups.append(nn.Conv2DTranspose(ch, ch, 4, stride=2,
                                              padding=1))
        self.ups = nn.LayerList(ups)
        self.out_norm = nn.GroupNorm(min(config.groups, ch), ch)
        self.out_conv = nn.Conv2D(ch, config.out_channels, 3, padding=1)

    def forward(self, x, t, context=None):
        import paddle_tpu as paddle
        temb = self.t2(F.silu(self.t1(
            timestep_embedding(t, self.temb_dim).astype(x.dtype))))
        h = self.inc(x)
        skips = [h]
        for blk in self.downs:
            if isinstance(blk, nn.LayerList):
                h = blk[0](h, temb)
                if len(blk) > 1:
                    h = blk[1](h, context)
            else:
                h = blk(h)                      # strided downsample
            skips.append(h)
        h = self.mid2(self.mid_attn(self.mid1(h, temb), context), temb)
        for blk in self.ups:
            if isinstance(blk, nn.LayerList):
                h = paddle.concat([h, skips.pop()], axis=1)
                h = blk[0](h, temb)
                if len(blk) > 1:
                    h = blk[1](h, context)
            else:
                h = blk(h)                      # transposed upsample
        return self.out_conv(F.silu(self.out_norm(h)))

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in self.parameters())


@functools.lru_cache(maxsize=8)
def _ddpm_alphas(num_steps: int, beta_start=1e-4, beta_end=2e-2):
    betas = np.linspace(beta_start, beta_end, num_steps, dtype=np.float32)
    return np.cumprod(1.0 - betas)


@functools.lru_cache(maxsize=8)
def _ddpm_alphas_t(num_steps: int):
    """Device-resident cumulative-alpha table (one upload per schedule)."""
    import paddle_tpu as paddle
    return paddle.to_tensor(_ddpm_alphas(num_steps))


def ddpm_loss(model, x0, t, noise, context=None, num_steps: int = 1000):
    """Noise-prediction MSE at timesteps t (DDPM eq. 14): the training
    objective of the diffusion family. x0 [B, C, H, W]; t [B] int;
    noise ~ N(0, 1) like x0."""
    abar = _ddpm_alphas_t(num_steps)
    a = abar[t].reshape([-1, 1, 1, 1]).astype(x0.dtype)
    xt = x0 * a.sqrt() + noise * (1.0 - a).sqrt()
    pred = model(xt, t, context)
    return ((pred - noise.astype(pred.dtype)) ** 2).mean()


def ddim_sample(model, shape, num_steps: int = 50, train_steps: int = 1000,
                context=None, seed: int = 0):
    """Deterministic DDIM sampler (eta=0) over a trained noise predictor.
    Returns x0 [B, C, H, W]. Serving-side: every model call has the same
    static shape, so one compiled forward serves all steps."""
    import paddle_tpu as paddle
    rng = np.random.RandomState(seed)
    abar = _ddpm_alphas(train_steps)
    ts = np.linspace(train_steps - 1, 0, num_steps).round().astype(np.int64)
    x = paddle.to_tensor(rng.randn(*shape).astype(np.float32))
    with paddle.no_grad():
        for i, ti in enumerate(ts):
            t = paddle.to_tensor(np.full((shape[0],), ti, np.int64))
            eps = model(x, t, context)
            a_t = float(abar[ti])
            x0 = (x - math.sqrt(1.0 - a_t) * eps) / math.sqrt(a_t)
            if i + 1 == len(ts):
                x = x0
            else:
                a_prev = float(abar[ts[i + 1]])
                x = (math.sqrt(a_prev) * x0
                     + math.sqrt(1.0 - a_prev) * eps)
    return x
