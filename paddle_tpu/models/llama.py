"""Llama model family (flagship; BASELINE.md config #3, Llama-2-7B).

The reference ships Llama through PaddleNLP on top of the fused-op tier
(fused rope: python/paddle/incubate/nn/functional/fused_rotary_position_embedding.py,
flash attention: paddle/phi/kernels/gpu/flash_attn_kernel.cu:128, rmsnorm in
fusion kernels). This is a TPU-first redesign, not a port:

- static shapes end to end, single fused attention contraction (XLA fuses
  the softmax chain; Pallas flash kernel swaps in on TPU),
- GQA (n_kv_heads < n_heads) expressed as an einsum over grouped heads so the
  MXU sees large batched matmuls,
- RoPE applied as a cheap elementwise rotation fused by XLA into the
  projection matmuls,
- optional tensor parallelism via the mp sharded layers (GSPMD inserts the
  Megatron collectives over ICI).
"""
from __future__ import annotations

import math
import os
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..nn.common import Embedding, Linear
from ..nn.norm import RMSNorm


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    initializer_range: float = 0.02
    dtype: str = "float32"
    # context parallelism: when set, attention runs as a ring over this mesh
    # axis (sequence sharded; exact global attention via ICI ppermute)
    sep_mesh: Optional[object] = None
    sep_axis: str = "sep"
    # sep_impl: "ring" (ppermute K/V rotation, any head count),
    # "ulysses" (all-to-all heads<->sequence — needs heads divisible by
    # the sep axis; one dense full-seq contraction per head subset), or
    # "auto" (ulysses when its shape contract holds, else ring —
    # ops.ulysses_attention.choose_sep_impl)
    sep_impl: str = "ring"
    # activation recompute: re-run each decoder layer's forward in the
    # backward instead of keeping its residuals (fleet/recompute analog —
    # trades ~30% step FLOPs for O(layers) less activation HBM)
    use_recompute: bool = False
    # recompute_granularity (reference knob on its recompute configs):
    #   "full"      — save only layer inputs, recompute everything
    #   "selective" — jax.checkpoint_policies.dots_with_no_batch_dims_
    #                 saveable: matmul outputs stay resident, only the
    #                 cheap elementwise/softmax work replays (the TPU
    #                 analog of the reference's core_attn tier: most of
    #                 the memory win at a fraction of the recompute FLOPs)
    recompute_granularity: str = "full"
    # scan_layers: run the decoder stack as ONE lax.scan over stacked
    # [L, ...] weights — the layer body is traced/compiled once, so XLA
    # compile time is O(1) in depth instead of O(L). The canonical TPU
    # pattern for deep stacks; numerics identical to the unrolled loop.
    scan_layers: bool = False
    # Mixture-of-experts MLP (Mixtral-style): num_experts > 1 replaces each
    # layer's SwiGLU with a routed expert bank (gshard top-k gate, stacked
    # expert weights, optional expert parallelism over ep_mesh/ep_axis —
    # GSPMD inserts the dispatch/combine collectives). The gate's
    # load-balancing aux loss is added to the LM loss with moe_aux_coeff.
    num_experts: int = 1
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_coeff: float = 0.01
    ep_mesh: Optional[object] = None
    ep_axis: str = "ep"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def llama2_7b_config(**overrides) -> LlamaConfig:
    cfg = LlamaConfig()
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


def llama_tiny_config(**overrides) -> LlamaConfig:
    """Test-scale config (the reference's tiny GPT fixture analog,
    test/auto_parallel/get_gpt_model.py)."""
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


def _rope_cos_sin(seq_len: int, head_dim: int, theta: float, dtype):
    """Precompute RoPE tables: [seq, head_dim//2]."""
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2,
                                          dtype=np.float32) / head_dim))
    t = np.arange(seq_len, dtype=np.float32)
    freqs = np.outer(t, inv_freq)  # [seq, hd/2]
    return (jnp.asarray(np.cos(freqs), dtype=dtype),
            jnp.asarray(np.sin(freqs), dtype=dtype))


def apply_rotary_pos_emb(x, cos, sin):
    """Rotate [B, S, H, D] by the (cos, sin) tables ([S, D/2]).

    Interleaved-pair convention (fused_rotary_position_embedding analog):
    even/odd feature pairs are rotated in fp32 then cast back — elementwise,
    so XLA fuses it into the surrounding matmuls.
    """
    x32 = x.astype(jnp.float32)
    x1 = x32[..., 0::2]
    x2 = x32[..., 1::2]
    c = cos[None, :, None, :].astype(jnp.float32)
    s = sin[None, :, None, :].astype(jnp.float32)
    r1 = x1 * c - x2 * s
    r2 = x2 * c + x1 * s
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


class LlamaAttention(Layer):
    """GQA attention with RoPE."""

    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        h, kv = config.num_attention_heads, config.num_key_value_heads
        d = config.head_dim
        init = I.Normal(std=config.initializer_range)
        self.q_proj = Linear(config.hidden_size, h * d, weight_attr=init,
                             bias_attr=False)
        self.k_proj = Linear(config.hidden_size, kv * d, weight_attr=init,
                             bias_attr=False)
        self.v_proj = Linear(config.hidden_size, kv * d, weight_attr=init,
                             bias_attr=False)
        self.o_proj = Linear(h * d, config.hidden_size, weight_attr=init,
                             bias_attr=False)

    def forward(self, hidden, cos, sin, attn_mask=None, return_kv=False):
        b, s, _ = hidden.shape
        cfg = self.config
        h, kv, d = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        q = self.q_proj(hidden).reshape([b, s, h, d])
        k = self.k_proj(hidden).reshape([b, s, kv, d])
        v = self.v_proj(hidden).reshape([b, s, kv, d])
        q = apply_rotary_pos_emb_t(q, cos, sin)
        k = apply_rotary_pos_emb_t(k, cos, sin)
        if return_kv:
            # decode-cache layout [B, KV, S, D], post-RoPE, unexpanded GQA
            kv_out = (k.transpose([0, 2, 1, 3]), v.transpose([0, 2, 1, 3]))
        if cfg.sep_mesh is not None:
            # context parallelism: exact global attention with K/V blocks
            # rotating the ICI ring (SURVEY.md §5's CP gap filler). GQA kv
            # heads stay unexpanded — the ring ships h/kv less K/V traffic.
            # Masked/padded batches ride the ring too: the mask's query rows
            # are sequence-sharded, each step slices the block's columns.
            # an explicit mask is the COMPLETE attention spec (callers bake
            # causality into it), matching the dense path's is_causal rule
            impl = getattr(cfg, "sep_impl", "ring")
            if impl == "auto":
                from ..distributed.auto_parallel import ProcessMesh
                from ..ops.ulysses_attention import choose_sep_impl
                jm = (cfg.sep_mesh.jax_mesh
                      if isinstance(cfg.sep_mesh, ProcessMesh)
                      else cfg.sep_mesh)
                impl = choose_sep_impl(
                    jm, cfg.sep_axis, h, kv, int(q.shape[1]),
                    attn_mask.shape[1] if attn_mask is not None else None)
            if impl == "ulysses":
                from ..ops.ulysses_attention import ulysses_attention
                out = ulysses_attention(q, k, v, mesh=cfg.sep_mesh,
                                        axis_name=cfg.sep_axis,
                                        causal=attn_mask is None,
                                        attn_mask=attn_mask)
            else:
                from ..ops.ring_attention import ring_attention
                out = ring_attention(q, k, v, mesh=cfg.sep_mesh,
                                     axis_name=cfg.sep_axis,
                                     causal=attn_mask is None,
                                     attn_mask=attn_mask)
        else:
            from ..nn.functional import _pallas_attention_eligible
            mask_arr = None if attn_mask is None else attn_mask._data
            if kv != h and not _pallas_attention_eligible(
                    q._data, k._data, mask_arr, 0.0):
                # GQA on the dense XLA path: repeat kv heads to full head
                # count; XLA keeps this as a broadcast feeding the batched
                # matmul (no copy). The Pallas kernel handles GQA natively.
                rep = h // kv
                k = k.unsqueeze(3).expand(
                    [b, s, kv, rep, d]).reshape([b, s, h, d])
                v = v.unsqueeze(3).expand(
                    [b, s, kv, rep, d]).reshape([b, s, h, d])
            out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                                 is_causal=attn_mask is None)
        out = out.reshape([b, s, h * d])
        out = self.o_proj(out)
        if return_kv:
            return out, kv_out[0], kv_out[1]
        return out


def apply_rotary_pos_emb_t(x: Tensor, cos, sin) -> Tensor:
    """Tensor-level RoPE wired through the op layer so autograd sees it."""
    from ..ops.registry import dispatch
    return dispatch(apply_rotary_pos_emb, (x, cos, sin), {}, "rope")


def _rope_at(x, cos_tab, sin_tab, t):
    """Rotate [B, H, D] by per-batch positions t [B] (decode step RoPE)."""
    c = cos_tab[t][:, None, :].astype(jnp.float32)   # [B, 1, D/2]
    s = sin_tab[t][:, None, :].astype(jnp.float32)
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., 0::2], xf[..., 1::2]
    r1 = x1 * c - x2 * s
    r2 = x2 * c + x1 * s
    return jnp.stack([r1, r2], axis=-1).reshape(x.shape).astype(x.dtype)


def _decode_attn(q, k_new, v_new, cache_k, cache_v, t, cos_tab, sin_tab):
    """One-token GQA decode over the dense cache (the serving hot op).

    q [B, H, D] (pre-RoPE); k_new/v_new [B, KV, D] (pre-RoPE);
    cache_k/v [B, KV, S_max, D] (post-RoPE rows); t [B] write positions.
    RoPE applies at position t, the new K/V row scatters in, and the
    attention runs grouped (GQA unexpanded — [B, KV, rep, D] against
    [B, KV, S, D]). Returns (ctx [B, H*D], cache_k', cache_v').
    Reference analog: masked_multihead_attention_kernel.cu, with GQA.
    """
    b, h, d = q.shape
    kvh = cache_k.shape[1]
    s_max = cache_k.shape[2]
    q = _rope_at(q, cos_tab, sin_tab, t)
    k_new = _rope_at(k_new, cos_tab, sin_tab, t)
    b_idx = jnp.arange(b)
    ck = cache_k.at[b_idx, :, t].set(k_new.astype(cache_k.dtype))
    cv = cache_v.at[b_idx, :, t].set(v_new.astype(cache_v.dtype))
    rep = h // kvh
    qg = q.reshape(b, kvh, rep, d)
    scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum("bgrd,bgsd->bgrs", qg.astype(jnp.float32),
                        ck.astype(jnp.float32)) * scale
    pos = jnp.arange(s_max)[None, None, None, :]
    scores = jnp.where(pos <= t[:, None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bgrs,bgsd->bgrd", probs, cv.astype(jnp.float32))
    return ctx.reshape(b, h * d).astype(q.dtype), ck, cv


class LlamaMLP(Layer):
    """SwiGLU MLP: down(silu(gate(x)) * up(x))."""

    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        init = I.Normal(std=config.initializer_range)
        self.gate_proj = Linear(config.hidden_size, config.intermediate_size,
                                weight_attr=init, bias_attr=False)
        self.up_proj = Linear(config.hidden_size, config.intermediate_size,
                              weight_attr=init, bias_attr=False)
        self.down_proj = Linear(config.intermediate_size, config.hidden_size,
                                weight_attr=init, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


def _make_expert_bank_cls():
    """Build the SwiGLU expert bank class lazily (the moe package imports
    back into models; a deferred class avoids the cycle at import time)."""
    from ..incubate.distributed.models.moe.moe_layer import _MoEBase

    class _LlamaExpertBank(_MoEBase):
        """Routed SwiGLU experts over stacked [E, h, I]/[E, I, h] weights."""

        def __init__(self, config: "LlamaConfig"):
            _MoEBase.__init__(
                self, config.hidden_size, config.num_experts,
                gate={"type": "gshard", "top_k": config.moe_top_k},
                capacity_factor=config.moe_capacity_factor,
                ep_mesh=config.ep_mesh,
                ep_axis=config.ep_axis if config.ep_mesh is not None
                else None)
            E, h, ims = (config.num_experts, config.hidden_size,
                         config.intermediate_size)
            init = I.Normal(std=config.initializer_range)
            self.gate_w = self.create_parameter([E, h, ims],
                                                default_initializer=init)
            self.up_w = self.create_parameter([E, h, ims],
                                              default_initializer=init)
            self.down_w = self.create_parameter([E, ims, h],
                                                default_initializer=init)
            if config.ep_mesh is not None:
                from ..distributed.auto_parallel import (Replicate, Shard,
                                                         shard_tensor)
                pl = [Shard(0) if n == config.ep_axis else Replicate()
                      for n in config.ep_mesh.dim_names]
                for p in (self.gate_w, self.up_w, self.down_w):
                    shard_tensor(p, config.ep_mesh, pl)

        def _run_experts(self, x):
            """x [E, C, h] → SwiGLU per expert (batched einsums)."""
            import paddle_tpu as paddle
            g = F.silu(paddle.einsum("ecd,edh->ech", x, self.gate_w))
            u = paddle.einsum("ecd,edh->ech", x, self.up_w)
            return paddle.einsum("ech,ehd->ecd", g * u, self.down_w)

    return _LlamaExpertBank


_EXPERT_BANK_CLS = None


class LlamaMoEMLP(Layer):
    """Mixtral-style routed SwiGLU expert bank.

    Stacked expert weights [E, h, I]/[E, I, h] with the shared MoE routing
    machinery (gshard top-k gate → dispatch [N,E,C] → per-expert SwiGLU →
    combine). Expert parallelism: with cfg.ep_mesh/ep_axis the expert dim
    is Shard(0) over the ep axis and GSPMD inserts the all-to-alls —
    reference surface: incubate/distributed/models/moe (moe_layer.py:263)
    composed with the llama FFN.
    """

    def __init__(self, config: LlamaConfig):
        global _EXPERT_BANK_CLS
        super().__init__(dtype=config.dtype)
        if _EXPERT_BANK_CLS is None:
            _EXPERT_BANK_CLS = _make_expert_bank_cls()
        self.moe = _EXPERT_BANK_CLS(config)

    @property
    def l_aux(self):
        return self.moe.l_aux

    def forward(self, x):
        return self.moe(x)


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.self_attn = LlamaAttention(config)
        self.mlp = (LlamaMoEMLP(config) if config.num_experts > 1
                    else LlamaMLP(config))
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       epsilon=config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)

    def forward(self, hidden, cos, sin, attn_mask=None):
        residual = hidden
        hidden = self.input_layernorm(hidden)
        hidden = self.self_attn(hidden, cos, sin, attn_mask)
        hidden = residual + hidden
        residual = hidden
        hidden = self.post_attention_layernorm(hidden)
        hidden = self.mlp(hidden)
        return residual + hidden

    def forward_kv(self, hidden, cos, sin):
        """Prefill: dense forward + this layer's post-RoPE K/V for the
        decode cache ([B, KV, S, D])."""
        attn_out, k, v = self.self_attn(self.input_layernorm(hidden),
                                        cos, sin, return_kv=True)
        hidden = hidden + attn_out
        return hidden + self.mlp(self.post_attention_layernorm(hidden)), k, v

    def decode(self, hidden, cache_kv, t, cos_tab, sin_tab):
        """One-token decode over the dense KV cache.

        hidden [B, 1, E]; cache_kv [2, B, KV, S_max, D]; t [B] int32.
        Returns (hidden', new_cache)."""
        from ..ops.registry import dispatch
        attn = self.self_attn
        cfg = attn.config
        b = hidden.shape[0]
        h, kvh, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                     cfg.head_dim)
        x = self.input_layernorm(hidden)
        q = attn.q_proj(x).reshape([b, h, d])
        k = attn.k_proj(x).reshape([b, kvh, d])
        v = attn.v_proj(x).reshape([b, kvh, d])
        ctx, ck, cv = dispatch(
            _decode_attn,
            (q, k, v, cache_kv[0], cache_kv[1], t, Tensor(cos_tab),
             Tensor(sin_tab)), {}, "llama_decode_attn")
        hidden = hidden + attn.o_proj(ctx.reshape([b, 1, h * d]))
        from .. import ops
        new_cache = ops.stack([ck, cv])
        return (hidden + self.mlp(self.post_attention_layernorm(hidden)),
                new_cache)


class ScannedLlamaLayers(Layer):
    """The whole decoder stack as ONE ``lax.scan``.

    Parameters are stacked [L, ...] arrays; the scan body (rmsnorm → GQA
    attention with RoPE → rmsnorm → SwiGLU) is traced exactly once, so XLA
    compile time stops growing with depth. ``remat`` re-runs each layer in
    the backward (jax.checkpoint inside scan = the recompute analog with
    O(1) compile). Flash attention (Pallas) slots into the body when
    eligible. Numerics match the unrolled LlamaDecoderLayer stack.
    """

    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.l_aux = None
        L = config.num_hidden_layers
        hs = config.hidden_size
        h, kv, d = (config.num_attention_heads, config.num_key_value_heads,
                    config.head_dim)
        ims = config.intermediate_size
        init = I.Normal(std=config.initializer_range)
        ones = I.Constant(1.0)

        def p(shape, initializer=init):
            return self.create_parameter(shape,
                                         default_initializer=initializer)

        self.q_w = p([L, hs, h * d])
        self.k_w = p([L, hs, kv * d])
        self.v_w = p([L, hs, kv * d])
        self.o_w = p([L, h * d, hs])
        if config.num_experts > 1:
            # routed SwiGLU expert bank, stacked over layers AND experts:
            # the scan body routes with this layer's [E, ...] slices (same
            # gshard top-2 + capacity machinery as the unrolled
            # _LlamaExpertBank, in pure jnp)
            if config.moe_top_k != 2:
                # same contract the unrolled path enforces via
                # GShardGate.__init__ — the gshard aux loss is a top-1
                # indicator over top-2 routing
                raise AssertionError("gshard gate requires top_k = 2")
            E = config.num_experts
            self.router_w = p([L, hs, E])
            self.router_b = p([L, E], I.Constant(0.0))
            self.moe_gate_w = p([L, E, hs, ims])
            self.moe_up_w = p([L, E, hs, ims])
            self.moe_down_w = p([L, E, ims, hs])
        else:
            self.gate_w = p([L, hs, ims])
            self.up_w = p([L, hs, ims])
            self.down_w = p([L, ims, hs])
        self.ln1_w = p([L, hs], ones)
        self.ln2_w = p([L, hs], ones)

    def forward(self, hidden, cos, sin, attn_mask=None):
        from ..core.flags import get_flag
        from ..ops import pallas as _pl
        from ..ops.registry import dispatch
        cfg = self.config
        h, kv, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                    cfg.head_dim)
        eps = cfg.rms_norm_eps
        seq = int(hidden.shape[1])
        ring_impl = None
        if cfg.sep_mesh is not None:
            # context parallelism inside the scan body: the ring shard_map
            # runs per scanned layer (scan-of-shard_map — the layer body is
            # still traced once; K/V blocks rotate the ICI ring each step)
            from ..distributed.auto_parallel import ProcessMesh
            from ..ops.ring_attention import (_DP_NAMES, _MP_NAMES,
                                              _cached_impl, _pick_axis)
            jmesh = (cfg.sep_mesh.jax_mesh
                     if isinstance(cfg.sep_mesh, ProcessMesh)
                     else cfg.sep_mesh)
            if seq % jmesh.shape[cfg.sep_axis]:
                raise ValueError(
                    f"sequence length {seq} not divisible by sep axis "
                    f"size {jmesh.shape[cfg.sep_axis]}")
            batch = int(hidden.shape[0])
            from ..ops.ring_attention import _axes_size
            batch_axis = _pick_axis(jmesh.axis_names, _DP_NAMES,
                                    cfg.sep_axis)
            head_axis = _pick_axis(jmesh.axis_names, _MP_NAMES, cfg.sep_axis)
            if batch_axis is not None and \
                    batch % _axes_size(jmesh, batch_axis):
                batch_axis = None
            if head_axis is not None and (
                    h % _axes_size(jmesh, head_axis)
                    or kv % _axes_size(jmesh, head_axis)):
                head_axis = None
            # explicit mask == complete attention spec (non-causal ring),
            # matching the dense branch's `mask is None` causality rule.
            # Flags passed positionally to share lru_cache slots with the
            # public ring_attention() call sites.
            sep_impl = getattr(cfg, "sep_impl", "ring")
            if sep_impl == "auto":
                from ..ops.ulysses_attention import choose_sep_impl
                sep_impl = choose_sep_impl(
                    jmesh, cfg.sep_axis, h, kv, seq,
                    attn_mask.shape[1] if attn_mask is not None else None)
            if sep_impl == "ulysses":
                # all-to-all CP (heads<->sequence): wins when heads are
                # plentiful (h, kv divisible by the sep axis) and a
                # P-step ring's per-hop latency would dominate; heads
                # shard jointly over (mp, sep) when divisible
                from ..ops.ulysses_attention import (
                    resolve_ulysses_head_axis, ulysses_attention_impl,
                    validate_ulysses)
                u_head_axis = resolve_ulysses_head_axis(
                    jmesh, cfg.sep_axis, head_axis, h, kv)
                validate_ulysses(
                    jmesh, cfg.sep_axis, h, kv, seq,
                    attn_mask.shape[1] if attn_mask is not None else None,
                    head_axis=u_head_axis)
                ring_impl = ulysses_attention_impl(
                    jmesh, cfg.sep_axis, causal=attn_mask is None,
                    batch_axis=batch_axis, head_axis=u_head_axis,
                    has_mask=attn_mask is not None,
                    mask_headed=attn_mask is not None
                    and attn_mask.shape[1] > 1,
                    has_seqlens=False)
            else:
                ring_impl = _cached_impl(jmesh, cfg.sep_axis,
                                         attn_mask is None,
                                         batch_axis, head_axis,
                                         attn_mask is not None, False)
        # PADDLE_TPU_FLASH_INTERPRET=1 routes the flash kernel interpreted
        # on the CPU mesh — the only way to exercise the exact bench
        # composition (flash x selective remat x scan) before a hardware
        # window; production routing stays TPU-only
        flash_interp = (os.environ.get("PADDLE_TPU_FLASH_INTERPRET") == "1"
                        and not _pl.on_tpu())
        use_flash = (ring_impl is None and attn_mask is None
                     and (_pl.on_tpu() or flash_interp)
                     and get_flag("FLAGS_use_pallas_attention"))
        if use_flash:
            from ..ops.pallas.flash_attention import supported
            use_flash = supported(seq, d)
        remat = cfg.use_recompute and self.training
        moe = cfg.num_experts > 1
        if moe:
            from ..incubate.distributed.models.moe.moe_layer import (
                _compute_capacity, moe_masks_jnp)
            E, top_k = cfg.num_experts, cfg.moe_top_k
            cap_factor = cfg.moe_capacity_factor

        def _impl(hidden, cos, sin, mask, qw, kw, vw, ow, *mlp_and_ln):
            if moe:
                rw, rb, mgw, muw, mdw, ln1, ln2 = mlp_and_ln
                mlp_ws = (rw, rb, mgw, muw, mdw)
            else:
                gw, uw, dw, ln1, ln2 = mlp_and_ln
                mlp_ws = (gw, uw, dw)

            def rms(x, w):
                xf = x.astype(jnp.float32)
                r = jax.lax.rsqrt(
                    jnp.mean(xf * xf, -1, keepdims=True) + eps)
                return (xf * r * w.astype(jnp.float32)).astype(x.dtype)

            def rope(x):
                # same pure-jnp RoPE as the unrolled path — ONE definition
                return apply_rotary_pos_emb(x, cos, sin)

            def mlp_dense(x2, ws):
                gw_, uw_, dw_ = ws
                return (jax.nn.silu(x2 @ gw_) * (x2 @ uw_)) @ dw_, 0.0

            def mlp_moe(x2, ws):
                """Routed SwiGLU experts — pure-jnp mirror of the unrolled
                _LlamaExpertBank (gshard top-2 probs, capacity priority
                masks, dense dispatch/combine einsums). Returns
                (mlp_out, this layer's aux loss)."""
                rw_, rb_, mgw_, muw_, mdw_ = ws
                b, s, hs_ = x2.shape
                n = b * s
                x2d = x2.reshape(n, hs_)
                probs = jax.nn.softmax(x2d @ rw_ + rb_, axis=-1)
                topk_val, topk_idx = jax.lax.top_k(probs, top_k)
                # gshard load-balance loss (top-1 indicator is constant)
                me = probs.astype(jnp.float32).mean(axis=0)
                ce = jax.lax.stop_gradient(jax.nn.one_hot(
                    topk_idx[:, 0], E, dtype=jnp.float32).mean(axis=0))
                aux_l = (me * ce).sum() * float(E)
                capacity = _compute_capacity(n, E, top_k, cap_factor)
                combine, dispatchm = moe_masks_jnp(
                    topk_val, topk_idx, num_experts=E, capacity=capacity,
                    norm_mode="sum")
                ein = jnp.einsum("nec,nd->ecd",
                                 dispatchm.astype(x2d.dtype), x2d)
                g = jax.nn.silu(jnp.einsum("ecd,edh->ech", ein, mgw_))
                u = jnp.einsum("ecd,edh->ech", ein, muw_)
                eo = jnp.einsum("ech,ehd->ecd", g * u, mdw_)
                out = jnp.einsum("nec,ecd->nd", combine.astype(eo.dtype), eo)
                return out.reshape(b, s, hs_), aux_l

            mlp_fn = mlp_moe if moe else mlp_dense

            def body_fn(carry, per_layer):
                h_, aux = carry
                (qw_, kw_, vw_, ow_, l1, l2), ws = per_layer
                b, s, _ = h_.shape
                x = rms(h_, l1)
                q = rope((x @ qw_).reshape(b, s, h, d))
                k = rope((x @ kw_).reshape(b, s, kv, d))
                v = (x @ vw_).reshape(b, s, kv, d)
                if ring_impl is not None:
                    # raw-jnp ring call (we are already inside the traced
                    # scan body; the op-level dispatch wrapper is above us)
                    ctx = (ring_impl(q, k, v) if mask is None
                           else ring_impl(q, k, v, mask))
                elif use_flash:
                    # GQA is native in the v2 kernel: K/V stay at kv heads
                    # (the index map expands the group in-kernel)
                    from ..ops.pallas.flash_attention import \
                        flash_attention_pallas
                    ctx = flash_attention_pallas(q, k, v, causal=True,
                                                 interpret=flash_interp)
                else:
                    if kv != h:
                        rep = h // kv
                        k = jnp.broadcast_to(k[:, :, :, None],
                                             (b, s, kv, rep, d)
                                             ).reshape(b, s, h, d)
                        v = jnp.broadcast_to(v[:, :, :, None],
                                             (b, s, kv, rep, d)
                                             ).reshape(b, s, h, d)
                    scale = 1.0 / (d ** 0.5)
                    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
                    if mask is not None:
                        if mask.dtype == jnp.bool_:
                            # keep/drop mask, matching _sdpa_op semantics
                            scores = jnp.where(
                                mask, scores,
                                jnp.finfo(jnp.float32).min)
                        else:
                            scores = scores + mask
                    else:
                        causal = jnp.tril(jnp.ones((s, s), bool))
                        scores = jnp.where(causal[None, None], scores, -1e9)
                    probs = jax.nn.softmax(
                        scores.astype(jnp.float32), -1).astype(h_.dtype)
                    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
                h1 = h_ + ctx.reshape(b, s, h * d) @ ow_
                x2 = rms(h1, l2)
                mlp, aux_l = mlp_fn(x2, ws)
                return (h1 + mlp, aux + aux_l), None

            if remat:
                gran = getattr(cfg, "recompute_granularity", "full")
                if gran in ("selective", "core_attn", "dots"):
                    body = jax.checkpoint(
                        body_fn,
                        policy=jax.checkpoint_policies
                        .dots_with_no_batch_dims_saveable)
                elif gran == "full":
                    body = jax.checkpoint(body_fn)
                else:
                    raise ValueError(
                        f"unknown recompute_granularity '{gran}' "
                        f"(use 'full' or 'selective')")
            else:
                body = body_fn
            xs = ((qw, kw, vw, ow, ln1, ln2), mlp_ws)
            (out, aux), _ = jax.lax.scan(
                body, (hidden, jnp.float32(0.0)), xs)
            return out, aux

        if moe:
            mlp_params = (self.router_w, self.router_b, self.moe_gate_w,
                          self.moe_up_w, self.moe_down_w)
        else:
            mlp_params = (self.gate_w, self.up_w, self.down_w)
        out, aux = dispatch(
            _impl,
            (hidden, Tensor(cos), Tensor(sin), attn_mask, self.q_w,
             self.k_w, self.v_w, self.o_w, *mlp_params,
             self.ln1_w, self.ln2_w),
            {}, op_name="llama_scanned_layers")
        # summed load-balance aux across the scanned stack; the LM head
        # adds moe_aux_coeff * l_aux exactly like the unrolled path
        self.l_aux = aux if moe else None
        return out


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.embed_tokens = Embedding(
            config.vocab_size, config.hidden_size,
            weight_attr=I.Normal(std=config.initializer_range))
        if config.scan_layers:
            self.layers_scanned = ScannedLlamaLayers(config)
            self.layers = []
        else:
            self.layers = [LlamaDecoderLayer(config)
                           for _ in range(config.num_hidden_layers)]
            for i, l in enumerate(self.layers):
                self.add_sublayer(f"layers.{i}", l)
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        jdt = dtype_mod.to_jax_dtype(config.dtype)
        self._cos, self._sin = _rope_cos_sin(
            config.max_position_embeddings, config.head_dim, config.rope_theta,
            jdt)

    def _anchor(self, hidden):
        """Re-anchor activation sharding at layer boundaries.

        ``shard_llama(..., batch_axes=, sep_axis=)`` installs an activation
        placement (batch over the data axes, sequence over the context-
        parallel axis, hidden replicated — the Megatron contract where
        row-parallel outputs are reduced over mp). Without the anchor, the
        eager discovery pass lets GSPMD pick a different output sharding per
        op and the batch-sharded residual meets an (seq, hidden)-sharded
        branch — an involuntary full rematerialization (reference analog:
        phi/infermeta/spmd_rules/* keep these transitions cheap by
        construction)."""
        anchor = getattr(self, "_act_anchor", None)
        if anchor is None:
            return hidden
        from ..distributed.auto_parallel import shard_tensor
        mesh, placements = anchor
        return shard_tensor(hidden, mesh, placements)

    def forward_prefill(self, input_ids, s_max):
        """Dense prompt pass that also fills the decode KV caches.

        Returns (hidden [B, S, E], caches [L, 2, B, KV, s_max, D]).
        Serving uses the unrolled stack (scan_layers exposes no per-layer
        K/V) and runs mesh-free (no sep ring)."""
        import paddle_tpu as paddle
        from .. import ops
        if self.config.scan_layers:
            raise ValueError("incremental decode needs the unrolled stack: "
                             "build the model with scan_layers=False for "
                             "serving")
        if self.config.sep_mesh is not None:
            # the ring would fill the cache through context-parallel
            # attention while decode attends a single dense cache — the
            # mismatch would be silent; refuse instead
            raise ValueError("incremental decode is mesh-free: clear "
                             "config.sep_mesh for serving (context "
                             "parallelism is a training-time layout)")
        b, s = input_ids.shape
        if s > s_max:
            raise ValueError(f"prompt length {s} exceeds cache size {s_max}")
        hidden = self.embed_tokens(input_ids)
        cos, sin = self._cos[:s], self._sin[:s]
        kvh, d = self.config.num_key_value_heads, self.config.head_dim
        pad = (paddle.zeros([b, kvh, s_max - s, d], dtype=self.config.dtype)
               if s < s_max else None)
        caches = []
        for layer in self.layers:
            hidden, k, v = layer.forward_kv(hidden, cos, sin)
            if pad is not None:
                k = ops.concat([k, pad.astype(k.dtype)], axis=2)
                v = ops.concat([v, pad.astype(v.dtype)], axis=2)
            caches.append(ops.stack([k, v]))
        return self.norm(hidden), ops.stack(caches)

    def forward(self, input_ids, attn_mask=None):
        _, s = input_ids.shape
        hidden = self.embed_tokens(input_ids)
        # NOTE: no anchor directly on the embedding output — a gather's
        # output sharding (hidden over fsdp, from the vocab-parallel table)
        # has no efficient reshard rule, and constraining it forces an
        # involuntary full rematerialization. The first layer's elementwise
        # and dot ops bridge to the anchored layout cheaply instead.
        cos, sin = self._cos[:s], self._sin[:s]
        if self.config.scan_layers:
            # one scan op: recompute (jax.checkpoint) handled inside
            hidden = self.layers_scanned(hidden, cos, sin, attn_mask)
            hidden = self._anchor(hidden)
        elif self.config.use_recompute and self.training:
            from ..distributed.fleet.recompute import recompute
            for layer in self.layers:
                trainable = any(not p.stop_gradient
                                for p in layer.parameters())
                hidden = recompute(layer, hidden, cos, sin, attn_mask,
                                   _trainable_hint=trainable)
                hidden = self._anchor(hidden)
        else:
            for layer in self.layers:
                hidden = layer(hidden, cos, sin, attn_mask)
                hidden = self._anchor(hidden)
        return self.norm(hidden)


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__(dtype=config.dtype)
        self.config = config
        self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  weight_attr=I.Normal(
                                      std=config.initializer_range),
                                  bias_attr=False)

    def forward(self, input_ids, labels=None, attn_mask=None):
        hidden = self.model(input_ids, attn_mask)
        if self.lm_head is None:
            from .. import ops
            logits = ops.matmul(hidden, self.model.embed_tokens.weight,
                                transpose_y=True)
        else:
            logits = self.lm_head(hidden)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]).astype("float32"),
            labels.reshape([-1]))
        if self.config.num_experts > 1 and self.config.moe_aux_coeff:
            if self.config.scan_layers:
                aux = self.model.layers_scanned.l_aux
                if aux is not None:
                    loss = loss + self.config.moe_aux_coeff * aux
            else:
                for layer in self.model.layers:
                    aux = getattr(layer.mlp, "l_aux", None)
                    if aux is not None:
                        loss = loss + self.config.moe_aux_coeff * aux
        return logits, loss

    # -- incremental (KV-cache) decode — the serving path -------------------

    def prefill(self, input_ids, s_max, n_valid=None):
        """Prompt pass for incremental decode. Returns
        (last_logits [B, 1, V], caches [L, 2, B, KV, s_max, D], t [B]).

        ``n_valid`` ([B, 1] int32): true prompt lengths when ``input_ids``
        is right-padded onto a bucket ladder — the final hidden state is
        gathered at n_valid-1 and decode resumes at t = n_valid (pad cache
        rows are overwritten before any decode step can attend them)."""
        import paddle_tpu as paddle
        b, s = input_ids.shape
        hidden, caches = self.model.forward_prefill(input_ids, s_max)
        if n_valid is None:
            last = hidden[:, s - 1:s]
            # t is [B, 1] — the shared decode-state convention (GPT-2 and
            # the serving batcher use the same shape)
            t = paddle.to_tensor(np.full((b, 1), s, np.int32))
        else:
            from .. import ops
            idx = (n_valid - 1).astype("int32").reshape([b, 1, 1])
            last = ops.take_along_axis(hidden, idx, axis=1)
            t = n_valid.astype("int32")
        logits = self._lm_logits(last)
        return logits, caches, t

    def _lm_logits(self, hidden):
        if self.lm_head is None:
            from .. import ops
            return ops.matmul(hidden, self.model.embed_tokens.weight,
                              transpose_y=True)
        return self.lm_head(hidden)

    def decode_step(self, tok, caches, t):
        """One incremental token through every layer's KV cache.

        tok [B, 1] int; caches [L, 2, B, KV, S_max, D]; t [B, 1] int32.
        Static shapes — ``jit.to_static(model.decode_step)`` compiles ONE
        executable that serves every step. Returns (logits, caches', t+1).
        """
        from .. import ops
        model = self.model
        hidden = model.embed_tokens(tok)           # [B, 1, E]
        cos_tab, sin_tab = model._cos, model._sin
        t_flat = t.reshape([-1])
        new_caches = []
        for i, layer in enumerate(model.layers):
            hidden, nc = layer.decode(hidden, caches[i], t_flat, cos_tab,
                                      sin_tab)
            new_caches.append(nc)
        hidden = model.norm(hidden)
        return self._lm_logits(hidden), ops.stack(new_caches), t + 1

    def generate(self, input_ids, max_new_tokens, s_max=None,
                 decode_fn=None, do_sample=False, temperature=1.0,
                 top_k=0, top_p=None, seed=None, eos_id=None, pad_id=None):
        """Incremental decode over the KV cache — greedy by default;
        ``do_sample`` draws with temperature / top-k / top-p, ``eos_id``
        stops rows early (shared driver semantics with the GPT-2 zoo)."""
        from .gpt import GPT2ForCausalLM
        _, s = input_ids.shape
        s_max = GPT2ForCausalLM._resolve_s_max(self.config, s,
                                               max_new_tokens, s_max)
        step = decode_fn if decode_fn is not None else self.decode_step
        return GPT2ForCausalLM._generate_loop(
            lambda: self.prefill(input_ids, s_max), step, input_ids,
            max_new_tokens, do_sample, temperature, top_k, top_p, seed,
            eos_id=eos_id, pad_id=pad_id)

    # -- paged-KV serving route (vLLM-style block cache, GQA-native) --------

    def _check_paged_servable(self):
        if self.config.scan_layers:
            raise ValueError("paged decode needs the unrolled stack: build "
                             "the model with scan_layers=False for serving")
        if self.config.sep_mesh is not None:
            raise ValueError("paged decode is mesh-free: clear "
                             "config.sep_mesh for serving")

    def paged_alloc(self, n_pages, block_size=64, cache_dtype=None):
        """Physical KV page pool: per layer, (kc, vc) of
        [n_pages, KV, block_size, D] — GQA caches at kv-head count
        (unexpanded), so the pool is H/KV times smaller than an
        MHA-equivalent one. After calibrate_cachekv_int8 the pools
        allocate int8 (half of bf16, quarter of fp32 cache HBM);
        cache_dtype overrides explicitly (dynamic-quant callers)."""
        import paddle_tpu as paddle
        cfg = self.config
        kvh, d = cfg.num_key_value_heads, cfg.head_dim
        dtype = cache_dtype or (
            "int8" if self._cachekv_scales is not None else cfg.dtype)
        return [(paddle.zeros([n_pages, kvh, block_size, d], dtype=dtype),
                 paddle.zeros([n_pages, kvh, block_size, d], dtype=dtype))
                for _ in range(cfg.num_hidden_layers)]

    _cachekv_scales = None

    def calibrate_cachekv_int8(self, sample_ids):
        """Install STATIC per-kv-head int8 cache scales from a calibration
        batch (reference cache_k_quant_scales surface, static mode): run
        the dense prefill, take each layer's per-head |K|/|V| amax over
        the post-RoPE rows, and store (quant=127/amax, dequant=amax/127)
        per layer. Afterwards every paged route — generate_paged and
        PagedContinuousBatcher — reads/writes an int8 page pool.
        Call with eval-mode weights; pass None to disable again."""
        if sample_ids is None:
            self._cachekv_scales = None
            return None
        import paddle_tpu as paddle
        from ..incubate.nn.functional.decode_attention import \
            cachekv_scales_from_dense as _cachekv_scales_from
        b, s = sample_ids.shape
        with paddle.no_grad():
            _, caches = self.model.forward_prefill(sample_ids, s)
        # caches [L, 2, B, KV, s, D] (post-RoPE rows, matching what the
        # paged route quantizes)
        self._cachekv_scales = _cachekv_scales_from(caches._data)
        return self._cachekv_scales

    def paged_prefill_into(self, input_ids, layers, block_tables,
                           block_size=64, dec_base=None, logits_at=None,
                           dynamic_cache_scales=False, cache_scales=None,
                           dynamic_scale_valid=None, logits_all=False):
        """Prompt pass writing post-RoPE K / raw V into a CALLER-OWNED page
        pool (block_gqa_attention in encoder mode). input_ids [B, s];
        block_tables [B, blocks_per_seq]. Returns (last_logits [B, V],
        new_layers) — the admission primitive for PagedContinuousBatcher.

        dec_base [B] int32 (optional): chunked-prefill append mode — see
        the GPT-2 docstring; RoPE positions follow the timeline
        (dec_base + local) inside the op, so chunks are exact.

        dynamic_cache_scales: dynamic cachekv-int8 prefill — the pools
        must be int8, each layer's op computes per-(sequence, head)
        scales from the prompt, and the return gains a third element:
        a per-layer list of scale dicts for paged_decode_step's
        state["cache_scales"]. dynamic_scale_valid [B] masks a chunked
        pad tail out of the scale statistics; cache_scales (per-layer
        dicts a first chunk returned) makes LATER chunks quantize with
        those same scales — the chunked x dynamic-int8 composition
        (reference: block_multihead_attention.py takes quant scales and
        chunked input in one op).
        """
        import paddle_tpu as paddle
        from ..incubate.nn.functional.decode_attention import (
            block_gqa_attention, cachekv_scale_kwargs as _scale_kwargs)

        if dynamic_cache_scales and cache_scales is not None:
            raise ValueError("dynamic_cache_scales computes scales; "
                             "cache_scales consumes them — pass one")
        self._check_paged_servable()
        cfg = self.config
        b, s = input_ids.shape
        h, kvh, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                     cfg.head_dim)
        if dec_base is None:
            enc = paddle.to_tensor(np.full((b,), s, np.int32))
            dec = paddle.to_tensor(np.zeros((b,), np.int32))
        else:
            enc = paddle.to_tensor(np.zeros((b,), np.int32))
            dec = dec_base
        this = paddle.to_tensor(np.full((b,), s, np.int32))
        cu_q = paddle.to_tensor(np.arange(b + 1, dtype=np.int32) * s)
        model = self.model
        cos_tab, sin_tab = model._cos, model._sin

        hidden = model.embed_tokens(input_ids)         # [B, s, E]
        layers_state = []
        scales_out = [] if dynamic_cache_scales else None
        for li, (layer, (kc, vc)) in enumerate(zip(model.layers, layers)):
            attn = layer.self_attn
            x = layer.input_layernorm(hidden)
            q = attn.q_proj(x).reshape([b * s, h, d])
            k = attn.k_proj(x).reshape([b * s, kvh, d])
            v = attn.v_proj(x).reshape([b * s, kvh, d])
            if dynamic_cache_scales:
                extra = dict(use_dynamic_cachekv_quant=True,
                             compute_dynamic_scales=True,
                             dynamic_scale_valid=dynamic_scale_valid)
            else:
                extra = _scale_kwargs(
                    cache_scales if cache_scales is not None
                    else self._cachekv_scales, li)
            res = block_gqa_attention(
                q, k, v, kc, vc, enc, dec, this, cu_q, block_tables,
                block_size=block_size, rope_cos=Tensor(cos_tab),
                rope_sin=Tensor(sin_tab), **extra)
            if dynamic_cache_scales:
                out, kc, vc, (kq, vq, kdq, vdq) = res
                scales_out.append({"kq": kq, "vq": vq,
                                   "kdq": kdq, "vdq": vdq})
            else:
                out, kc, vc = res
            hidden = hidden + attn.o_proj(out.reshape([b, s, h * d]))
            hidden = hidden + layer.mlp(
                layer.post_attention_layernorm(hidden))
            layers_state.append((kc, vc))
        hidden = model.norm(hidden)
        if logits_all:
            # speculative verify: score every appended position in one
            # pass (s = draft_k + 1)
            logits = self._lm_logits(hidden)             # [b, s, V]
        elif logits_at is not None:
            # chunked prefill: project ONLY the requested position (the
            # lm head over all C positions would be C x the needed FLOPs)
            oh = F.one_hot(logits_at.reshape([b]).astype("int64"),
                           s).astype(hidden.dtype)
            logits = self._lm_logits(paddle.einsum("bs,bse->be", oh,
                                                   hidden))
        else:
            logits = self._lm_logits(hidden[:, s - 1])
        if dynamic_cache_scales:
            return logits, layers_state, scales_out
        return logits, layers_state

    def _layer_cache_scales(self, li):
        """block_gqa_attention kwargs for layer li's cache quantization
        (empty when the int8 cache is disabled)."""
        from ..incubate.nn.functional.decode_attention import \
            cachekv_scale_kwargs
        return cachekv_scale_kwargs(self._cachekv_scales, li)

    def paged_prefill(self, input_ids, block_size=64, blocks_per_seq=None):
        """Prompt pass through a freshly allocated paged cache. Returns
        (last_logits [B, V], state dict) in the shared paged-state
        convention (same keys as the GPT-2 route, so one batcher and one
        compiled-step recipe serve both families)."""
        from .gpt import GPT2ForCausalLM
        return GPT2ForCausalLM._paged_prefill_impl(self, input_ids,
                                                   block_size,
                                                   blocks_per_seq)

    def paged_decode_step(self, tok, state):
        """One token per sequence through the paged GQA cache. tok: [B].
        Static shapes — ``jit.to_static(model.paged_decode_step)`` serves
        every step with one executable."""
        from ..incubate.nn.functional.decode_attention import \
            block_gqa_attention

        self._check_paged_servable()
        cfg = self.config
        b = tok.shape[0]
        h, kvh, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                     cfg.head_dim)
        t = state["dec_lens"]
        bt = state["block_tables"]
        enc, this, cu_q = state["zeros_b"], state["ones_b"], state["cu_b"]
        model = self.model
        cos_tab, sin_tab = model._cos, model._sin

        hidden = model.embed_tokens(tok.reshape([b, 1]))   # [B, 1, E]
        dyn = state.get("cache_scales")
        new_layers = []
        for li, (layer, (kc, vc)) in enumerate(zip(model.layers,
                                                   state["layers"])):
            attn = layer.self_attn
            x = layer.input_layernorm(hidden)
            q = attn.q_proj(x).reshape([b, h, d])
            k = attn.k_proj(x).reshape([b, kvh, d])
            v = attn.v_proj(x).reshape([b, kvh, d])
            if dyn is not None:
                # dynamic cachekv int8: per-(slot, head) scales ride the
                # state, fixed by each sequence's prefill
                from ..incubate.nn.functional.decode_attention import \
                    cachekv_scale_kwargs
                kwargs = dict(cachekv_scale_kwargs(dyn, li),
                              use_dynamic_cachekv_quant=True)
            else:
                kwargs = self._layer_cache_scales(li)
            out, kc, vc = block_gqa_attention(
                q, k, v, kc, vc, enc, t, this, cu_q, bt,
                block_size=state["block_size"], rope_cos=Tensor(cos_tab),
                rope_sin=Tensor(sin_tab), **kwargs)
            hidden = hidden + attn.o_proj(out.reshape([b, 1, h * d]))
            hidden = hidden + layer.mlp(
                layer.post_attention_layernorm(hidden))
            new_layers.append((kc, vc))
        hidden = model.norm(hidden)
        logits = self._lm_logits(hidden[:, 0])             # [B, V]
        new_state = dict(state, layers=new_layers, dec_lens=t + 1)
        return logits, new_state

    def paged_fused_step(self, tok, chunk_ids, chunk_bt, chunk_dec,
                         chunk_at, state):
        """ONE packed call advancing every decode slot AND one admission
        chunk (vLLM unified/continuous scheduling: decode never stalls
        while a prompt prefills).

        tok [B]: this step's decode tokens (parked slots carry garbage).
        chunk_ids [C]: the admission chunk (zeros when nothing admits).
        chunk_bt [1, bps]: the admitting sequence's block-table row (all
        scratch when idle). chunk_dec [1]: rows already written by prior
        chunks. chunk_at [1]: position of the last real token within this
        chunk (for its logits). The packed batch is B+1 sequences /
        B+C tokens: sequences 0..B-1 decode (this=1), sequence B is the
        chunk (this=C); ONE executable serves every occupancy and every
        prompt length. Returns (decode_logits [B, V], chunk_logits
        [1, V], new_state).
        """
        import paddle_tpu as paddle
        from .. import ops
        from ..incubate.nn.functional.decode_attention import \
            block_gqa_attention

        self._check_paged_servable()
        cfg = self.config
        b = tok.shape[0]
        c = chunk_ids.shape[0]
        h, kvh, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                     cfg.head_dim)
        t = state["dec_lens"]
        bt = ops.concat([state["block_tables"], chunk_bt], axis=0)
        enc = paddle.to_tensor(np.zeros((b + 1,), np.int32))
        this = paddle.to_tensor(
            np.concatenate([np.ones((b,), np.int32), [c]]).astype(np.int32))
        dec_call = ops.concat([t, chunk_dec], axis=0)
        cu_q = paddle.to_tensor(np.concatenate(
            [np.arange(b + 1, dtype=np.int32), [b + c]]).astype(np.int32))
        model = self.model
        cos_tab, sin_tab = model._cos, model._sin

        all_tok = ops.concat([tok.reshape([b]), chunk_ids.reshape([c])],
                             axis=0)
        hidden = model.embed_tokens(all_tok)              # [B+C, E]
        dyn = state.get("cache_scales")
        new_layers = []
        for li, (layer, (kc, vc)) in enumerate(zip(model.layers,
                                                   state["layers"])):
            attn = layer.self_attn
            x = layer.input_layernorm(hidden)
            q = attn.q_proj(x).reshape([b + c, h, d])
            k = attn.k_proj(x).reshape([b + c, kvh, d])
            v = attn.v_proj(x).reshape([b + c, kvh, d])
            if dyn is not None:
                # the chunk sequence (row B) has no per-slot scale row;
                # the batcher gates this combination up front
                raise NotImplementedError(
                    "fused admission + dynamic cachekv quant: use static "
                    "calibration (calibrate_cachekv_int8)")
            kwargs = self._layer_cache_scales(li)
            out, kc, vc = block_gqa_attention(
                q, k, v, kc, vc, enc, dec_call, this, cu_q, bt,
                block_size=state["block_size"], rope_cos=Tensor(cos_tab),
                rope_sin=Tensor(sin_tab), **kwargs)
            hidden = hidden + attn.o_proj(out.reshape([b + c, h * d]))
            hidden = hidden + layer.mlp(
                layer.post_attention_layernorm(hidden))
            new_layers.append((kc, vc))
        hidden = model.norm(hidden)
        dec_logits = self._lm_logits(hidden[:b])          # [B, V]
        chunk_h = hidden[b:]                              # [C, E]
        oh = F.one_hot(chunk_at.reshape([1]).astype("int64"),
                       c).astype(chunk_h.dtype)           # [1, C]
        chunk_logits = self._lm_logits(
            paddle.einsum("oc,ce->oe", oh, chunk_h))      # [1, V]
        new_state = dict(state, layers=new_layers, dec_lens=t + 1)
        return dec_logits, chunk_logits, new_state

    def generate_paged(self, input_ids, max_new_tokens, block_size=64,
                       blocks_per_seq=None, decode_fn=None):
        """Greedy decode over the paged GQA cache (shared driver with
        GPT-2; reference surface block_multihead_attention + the serving
        predictor)."""
        from .gpt import GPT2ForCausalLM
        return GPT2ForCausalLM._paged_generate_loop(
            self, input_ids, max_new_tokens, block_size, blocks_per_seq,
            decode_fn)

    def generate_paged_speculative(self, input_ids, max_new_tokens,
                                   draft_model, draft_k=4, block_size=64,
                                   eos_id=None, compile=True,
                                   return_stats=False):
        """Greedy speculative decoding (shared loop with GPT-2): any
        draft sharing this model's vocab works — including a GPT-2-family
        draft for a Llama target, since both speak the shared paged-state
        convention. Token-exact vs generate()/generate_paged()."""
        from .gpt import GPT2ForCausalLM
        return GPT2ForCausalLM._speculative_loop(
            self, draft_model, input_ids, max_new_tokens, draft_k,
            block_size, eos_id, compile, return_stats)

    def generate_beam(self, input_ids, max_new_tokens, num_beams=4,
                      s_max=None, decode_fn=None, length_penalty=0.0):
        """Beam search over the GQA KV cache (shared driver with GPT-2)."""
        from .gpt import GPT2ForCausalLM
        _, s = input_ids.shape
        s_max = GPT2ForCausalLM._resolve_s_max(self.config, s,
                                               max_new_tokens, s_max)
        step = decode_fn if decode_fn is not None else self.decode_step
        return GPT2ForCausalLM._beam_loop(
            lambda ids: self.prefill(ids, s_max), step, input_ids,
            max_new_tokens, num_beams, length_penalty)

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in self.parameters())


def shard_llama(model: "LlamaForCausalLM", mesh, mp_axis: str = "mp",
                fsdp_axis: Optional[str] = None,
                batch_axes: Optional[Sequence[str]] = None,
                sep_axis: Optional[str] = None,
                ep_axis: str = "ep"):
    """Apply Megatron-style TP (+ optional FSDP) placements to a Llama model.

    The reference expresses this with dedicated parallel layer classes
    (fleet/layers/mpu/mp_layers.py) and per-op collectives; TPU-first the same
    plan is pure sharding metadata — GSPMD inserts the identity/allreduce/
    allgather collectives over ICI:
      - q/k/v/gate/up projections: column-parallel  -> Shard(out_dim) on mp
      - o/down projections:        row-parallel     -> Shard(in_dim)  on mp
      - token embedding:           vocab-parallel   -> Shard(vocab)   on mp
      - lm_head:                   column-parallel  -> Shard(vocab)   on mp
      - optional fsdp axis: every 2D weight additionally Shard on its other
        dim (ZeRO-3-style parameter sharding as placements, SURVEY.md §7).
      - optional batch_axes/sep_axis: install the activation anchor
        (batch over batch_axes, sequence over sep_axis, hidden replicated)
        that LlamaModel re-applies at every layer boundary so GSPMD never
        drifts into an involuntary full rematerialization.
    """
    from ..distributed.auto_parallel import Replicate, Shard, shard_tensor

    names = mesh.dim_names

    def place(param, mp_dim=None, fsdp_dim=None, ep_dim=None):
        placements = []
        for ax in names:
            if ax == mp_axis and mp_dim is not None:
                placements.append(Shard(mp_dim))
            elif fsdp_axis is not None and ax == fsdp_axis \
                    and fsdp_dim is not None:
                placements.append(Shard(fsdp_dim))
            elif ax == ep_axis and ep_dim is not None:
                placements.append(Shard(ep_dim))
            else:
                placements.append(Replicate())
        shard_tensor(param, mesh, placements)

    # Embedding: vocab-parallel over BOTH mp and fsdp (Megatron
    # VocabParallelEmbedding, fleet/layers/mpu/mp_layers.py) — never the
    # hidden dim. A gather from a hidden-sharded table has no efficient
    # GSPMD reshard to the (batch, seq)-sharded activation layout
    # (involuntary full remat); a vocab-sharded table partitions the
    # lookup along the index sharding plus one allreduce.
    place(model.model.embed_tokens.weight, mp_dim=0, fsdp_dim=0)
    if model.config.scan_layers:
        # stacked [L, in, out] weights: the layer dim leads, so the 2D
        # placements shift by one (same TP plan, scan-compatible)
        sc = model.model.layers_scanned
        if model.config.num_experts > 1:
            # stacked [L, E, in, out] expert banks: expert dim Shard(1)
            # over ep, TP/FSDP shift one more for the leading layer dim;
            # the router stays replicated (same invariant as unrolled)
            for col in (sc.q_w, sc.k_w, sc.v_w):
                place(col, mp_dim=2, fsdp_dim=1)
            place(sc.o_w, mp_dim=1, fsdp_dim=2)
            place(sc.moe_gate_w, mp_dim=3, fsdp_dim=2, ep_dim=1)
            place(sc.moe_up_w, mp_dim=3, fsdp_dim=2, ep_dim=1)
            place(sc.moe_down_w, mp_dim=2, fsdp_dim=3, ep_dim=1)
            place(sc.router_w)
            place(sc.router_b)
        else:
            for col in (sc.q_w, sc.k_w, sc.v_w, sc.gate_w, sc.up_w):
                place(col, mp_dim=2, fsdp_dim=1)
            for row in (sc.o_w, sc.down_w):
                place(row, mp_dim=1, fsdp_dim=2)
        place(sc.ln1_w)
        place(sc.ln2_w)
    else:
        for layer in model.model.layers:
            attn, mlp = layer.self_attn, layer.mlp
            cols = [attn.q_proj, attn.k_proj, attn.v_proj]
            rows = [attn.o_proj]
            if isinstance(mlp, LlamaMoEMLP):
                # expert dim Shard(0) over ep; TP splits each expert's FFN
                # dims, FSDP takes the other dim; the router's tiny linear
                # is replicated EXPLICITLY so every parameter of an MoE
                # model carries a placement (dist-checkpoint audits rely
                # on that invariant)
                place(mlp.moe.gate_w, mp_dim=2, fsdp_dim=1, ep_dim=0)
                place(mlp.moe.up_w, mp_dim=2, fsdp_dim=1, ep_dim=0)
                place(mlp.moe.down_w, mp_dim=1, fsdp_dim=2, ep_dim=0)
                place(mlp.moe.gate.gate.weight)
                if mlp.moe.gate.gate.bias is not None:
                    place(mlp.moe.gate.gate.bias)
            else:
                cols += [mlp.gate_proj, mlp.up_proj]
                rows.append(mlp.down_proj)
            for col in cols:
                place(col.weight, mp_dim=1, fsdp_dim=0)
            for row in rows:
                place(row.weight, mp_dim=0, fsdp_dim=1)
            place(layer.input_layernorm.weight)
            place(layer.post_attention_layernorm.weight)
    place(model.model.norm.weight)
    if model.lm_head is not None:
        place(model.lm_head.weight, mp_dim=1, fsdp_dim=0)
    if batch_axes or sep_axis:
        act = []
        for ax in names:
            if batch_axes and ax in batch_axes and mesh.get_dim_size(ax) > 1:
                act.append(Shard(0))
            elif sep_axis and ax == sep_axis and mesh.get_dim_size(ax) > 1:
                act.append(Shard(1))
            else:
                act.append(Replicate())
        model.model._act_anchor = (mesh, act)
    return model
