"""Model zoo.

The reference keeps end-to-end model fixtures in test/ (e.g.
test/auto_parallel/get_gpt_model.py, test/book/) and vision models in
python/paddle/vision/models; its north-star configs (BASELINE.md) are
ResNet-50, GPT-2 124M, and Llama-2 7B. This package provides those model
families as first-class citizens, built TPU-first: static shapes, bf16-friendly
compute, attention through the fused flash-attention path, and optional
tensor-parallel variants over the hybrid mesh.
"""
from .bert import (BertConfig, BertForMaskedLM,
                   BertForSequenceClassification, BertModel,
                   bert_base_config, bert_tiny_config, shard_bert)
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel,
                    llama2_7b_config, llama_tiny_config, shard_llama)
from .gpt import GPT2Config, GPT2ForCausalLM, GPT2Model, gpt2_124m_config
from .resnet import (BasicBlock, BottleneckBlock, ResNet, resnet18, resnet34,
                     resnet50, resnet101, resnet152)
from .unet import (UNetConfig, UNetModel, ddim_sample, ddpm_loss,
                   sd_unet_config, unet_tiny_config)

__all__ = [
    "LlamaConfig", "LlamaModel", "LlamaForCausalLM", "llama2_7b_config",
    "llama_tiny_config", "shard_llama",
    "GPT2Config", "GPT2Model", "GPT2ForCausalLM", "gpt2_124m_config",
    "BertConfig", "BertModel", "BertForSequenceClassification",
    "BertForMaskedLM", "bert_base_config", "bert_tiny_config", "shard_bert",
    "ResNet", "BasicBlock", "BottleneckBlock", "resnet18", "resnet34",
    "resnet50", "resnet101", "resnet152",
    "UNetConfig", "UNetModel", "unet_tiny_config", "sd_unet_config",
    "ddpm_loss", "ddim_sample",
]
