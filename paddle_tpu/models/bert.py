"""BERT model family (encoder-only).

Reference: PaddleNLP-style BERT served by the framework's layer stack
(python/paddle/nn: MultiHeadAttention/TransformerEncoder are the building
blocks; test fixtures like test/legacy_test/test_transformer_api.py
exercise the same architecture). TPU-first: post-LN encoder blocks whose
attention runs through F.scaled_dot_product_attention (Pallas flash path
on TPU), bidirectional (is_causal=False) with an additive padding mask.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.common import Dropout, Embedding, Linear
from ..nn.layer import Layer
from ..nn.norm import LayerNorm


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    pad_token_id: int = 0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def bert_base_config(**overrides) -> BertConfig:
    return BertConfig(**overrides)


def bert_tiny_config(**overrides) -> BertConfig:
    base = dict(vocab_size=1024, hidden_size=128, num_hidden_layers=2,
                num_attention_heads=2, intermediate_size=512,
                max_position_embeddings=128, hidden_dropout_prob=0.0,
                attention_probs_dropout_prob=0.0)
    base.update(overrides)
    return BertConfig(**base)


class BertEmbeddings(Layer):
    """word + position + token-type embeddings, post-LN."""

    def __init__(self, config: BertConfig):
        super().__init__()
        init = I.Normal(std=config.initializer_range)
        self.word_embeddings = Embedding(config.vocab_size,
                                         config.hidden_size,
                                         weight_attr=init)
        self.position_embeddings = Embedding(config.max_position_embeddings,
                                             config.hidden_size,
                                             weight_attr=init)
        self.token_type_embeddings = Embedding(config.type_vocab_size,
                                               config.hidden_size,
                                               weight_attr=init)
        self.layer_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        from .. import ops
        _, s = input_ids.shape
        pos = ops.arange(0, s, dtype="int32").unsqueeze(0)
        emb = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertSelfAttention(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        init = I.Normal(std=config.initializer_range)
        h = config.hidden_size
        self.qkv = Linear(h, 3 * h, weight_attr=init)
        self.out = Linear(h, h, weight_attr=init)
        self.config = config
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, hidden, attn_mask=None):
        b, s, _ = hidden.shape
        h, d = self.config.num_attention_heads, self.config.head_dim
        qkv = self.qkv(hidden).reshape([b, s, 3, h, d])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=False,
            dropout_p=(self.config.attention_probs_dropout_prob
                       if self.training else 0.0))
        return self.dropout(self.out(out.reshape([b, s, h * d])))


class BertLayer(Layer):
    """Post-LN encoder block (original BERT residual ordering)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        init = I.Normal(std=config.initializer_range)
        self.attention = BertSelfAttention(config)
        self.attn_norm = LayerNorm(config.hidden_size,
                                   epsilon=config.layer_norm_eps)
        self.intermediate = Linear(config.hidden_size,
                                   config.intermediate_size,
                                   weight_attr=init)
        self.output = Linear(config.intermediate_size, config.hidden_size,
                             weight_attr=init)
        self.out_norm = LayerNorm(config.hidden_size,
                                  epsilon=config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, hidden, attn_mask=None):
        hidden = self.attn_norm(hidden + self.attention(hidden, attn_mask))
        ffn = self.dropout(self.output(F.gelu(self.intermediate(hidden))))
        return self.out_norm(hidden + ffn)


class BertPooler(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = Linear(config.hidden_size, config.hidden_size,
                            weight_attr=I.Normal(
                                std=config.initializer_range))

    def forward(self, hidden):
        return F.tanh(self.dense(hidden[:, 0]))


class BertModel(Layer):
    """Encoder backbone: (sequence_output, pooled_output)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.layers = [BertLayer(config)
                       for _ in range(config.num_hidden_layers)]
        for i, l in enumerate(self.layers):
            self.add_sublayer(f"layer.{i}", l)
        self.pooler = BertPooler(config)

    def _extend_mask(self, attention_mask):
        """[B, S] 1/0 padding mask -> additive [B, 1, S, S] bias."""
        if attention_mask is None:
            return None

        def _impl(m):
            bias = (1.0 - m.astype(jnp.float32)) * -1e9
            return bias[:, None, None, :]

        from ..ops.registry import dispatch
        return dispatch(_impl, (attention_mask,), {}, op_name="bert_mask")

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        hidden = self.embeddings(input_ids, token_type_ids)
        mask = self._extend_mask(attention_mask)
        for layer in self.layers:
            hidden = layer(hidden, mask)
        return hidden, self.pooler(hidden)

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in self.parameters())


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, num_classes,
                                 weight_attr=I.Normal(
                                     std=config.initializer_range))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            loss = F.cross_entropy(logits, labels)
            return logits, loss
        return logits


class BertForMaskedLM(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.transform = Linear(config.hidden_size, config.hidden_size,
                                weight_attr=I.Normal(
                                    std=config.initializer_range))
        self.norm = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_eps)
        self.decoder = Linear(config.hidden_size, config.vocab_size,
                              weight_attr=I.Normal(
                                  std=config.initializer_range))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None, ignore_index=-100):
        seq, _ = self.bert(input_ids, token_type_ids, attention_mask)
        hidden = self.norm(F.gelu(self.transform(seq)))
        logits = self.decoder(hidden)
        if labels is not None:
            b, s, v = logits.shape
            loss = F.cross_entropy(logits.reshape([b * s, v]),
                                   labels.reshape([b * s]),
                                   ignore_index=ignore_index)
            return logits, loss
        return logits


def shard_bert(model: BertModel, mesh, mp_axis: str = "mp",
               fsdp_axis=None):
    """Megatron placements for the encoder: qkv/intermediate column-split,
    out/output row-split, embeddings vocab-split (shard_llama analog)."""
    from ..distributed.auto_parallel import Replicate, Shard, shard_tensor

    def repl():
        return [Replicate() for _ in mesh.dim_names]

    def shard_on(axis_name, dim):
        return [Shard(dim) if n == axis_name else Replicate()
                for n in mesh.dim_names]

    bert = model.bert if hasattr(model, "bert") else model
    shard_tensor(bert.embeddings.word_embeddings.weight, mesh,
                 shard_on(mp_axis, 0))
    for layer in bert.layers:
        shard_tensor(layer.attention.qkv.weight, mesh, shard_on(mp_axis, 1))
        shard_tensor(layer.attention.out.weight, mesh, shard_on(mp_axis, 0))
        shard_tensor(layer.intermediate.weight, mesh, shard_on(mp_axis, 1))
        shard_tensor(layer.output.weight, mesh, shard_on(mp_axis, 0))
    if fsdp_axis:
        for p in bert.parameters():
            if p._dist_attr is None and p.ndim > 0 and \
                    p.shape[0] % mesh.get_dim_size(fsdp_axis) == 0:
                shard_tensor(p, mesh, shard_on(fsdp_axis, 0))
    return model


__all__ = ["BertConfig", "BertModel", "BertForSequenceClassification",
           "BertForMaskedLM", "bert_base_config", "bert_tiny_config",
           "shard_bert"]
