"""GPT-2 model family (BASELINE.md config #2, GPT-2 124M compiled-path bench).

Reference fixture: test/auto_parallel/get_gpt_model.py and the fused
transformer tier (phi/kernels/fusion). TPU-first: pre-norm blocks, learned
positional embeddings, GELU MLP, attention through the fused SDPA path.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..nn.common import Dropout, Embedding, Linear
from ..nn.norm import LayerNorm


# shared cachekv-int8 calibration helpers live beside the scale contract
# in incubate.nn.functional.decode_attention (model-agnostic)
from ..incubate.nn.functional.decode_attention import (  # noqa: E402
    cachekv_scale_kwargs as _cache_scale_kwargs,
    cachekv_scales_from_dense as _cachekv_scales_from)


@dataclass
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    dropout: float = 0.1
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def gpt2_124m_config(**overrides) -> GPT2Config:
    cfg = GPT2Config()
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


class GPT2Attention(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        init = I.Normal(std=config.initializer_range)
        self.c_attn = Linear(config.hidden_size, 3 * config.hidden_size,
                             weight_attr=init)
        self.c_proj = Linear(config.hidden_size, config.hidden_size,
                             weight_attr=init)
        self.config = config
        self.resid_dropout = Dropout(config.dropout)

    def forward(self, hidden, return_kv=False):
        b, s, _ = hidden.shape
        h, d = self.config.num_attention_heads, self.config.head_dim
        qkv = self.c_attn(hidden).reshape([b, s, 3, h, d])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=self.config.dropout if self.training else 0.0)
        out = self.c_proj(out.reshape([b, s, h * d]))
        out = self.resid_dropout(out)
        if return_kv:
            # cache layout [B, H, S, D] (masked_multihead_attention's)
            return out, k.transpose([0, 2, 1, 3]), v.transpose([0, 2, 1, 3])
        return out


class GPT2MLP(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        init = I.Normal(std=config.initializer_range)
        self.c_fc = Linear(config.hidden_size, config.intermediate_size,
                           weight_attr=init)
        self.c_proj = Linear(config.intermediate_size, config.hidden_size,
                             weight_attr=init)
        self.dropout = Dropout(config.dropout)

    def forward(self, x):
        return self.dropout(self.c_proj(F.gelu(self.c_fc(x), approximate=True)))


class GPT2Block(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.attn = GPT2Attention(config)
        self.ln_2 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.mlp = GPT2MLP(config)

    def forward(self, hidden):
        hidden = hidden + self.attn(self.ln_1(hidden))
        return hidden + self.mlp(self.ln_2(hidden))

    def forward_kv(self, hidden):
        """Prefill: dense causal attention + this layer's K/V for the cache."""
        attn_out, k, v = self.attn(self.ln_1(hidden), return_kv=True)
        hidden = hidden + attn_out
        return hidden + self.mlp(self.ln_2(hidden)), k, v

    def decode(self, hidden, cache_kv, t):
        """One-token decode over the dense KV cache.

        hidden: [B, 1, E]; cache_kv: [2, B, H, S_max, D]; t: [B, 1] current
        lengths. The attention is masked_multihead_attention (reference
        masked_multihead_attention.py:19 / its fused CUDA kernel) — scatter
        this step's K/V at row t, attend over the prefix. Returns
        (hidden', new_cache).
        """
        from ..incubate.nn.functional.decode_attention import \
            masked_multihead_attention
        b = hidden.shape[0]
        x = self.ln_1(hidden)
        qkv = self.attn.c_attn(x.reshape([b, -1]))       # [B, 3*H*D]
        out, new_cache = masked_multihead_attention(
            qkv, cache_kv, sequence_lengths=t)
        attn_out = self.attn.resid_dropout(
            self.attn.c_proj(out.reshape([b, 1, -1])))
        hidden = hidden + attn_out
        return hidden + self.mlp(self.ln_2(hidden)), new_cache


class GPT2Model(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.config = config
        init = I.Normal(std=config.initializer_range)
        self.wte = Embedding(config.vocab_size, config.hidden_size,
                             weight_attr=init)
        self.wpe = Embedding(config.max_position_embeddings,
                             config.hidden_size, weight_attr=init)
        self.drop = Dropout(config.dropout)
        self.h = [GPT2Block(config) for _ in range(config.num_hidden_layers)]
        for i, blk in enumerate(self.h):
            self.add_sublayer(f"h.{i}", blk)
        self.ln_f = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids):
        from .. import ops
        _, s = input_ids.shape
        pos = ops.arange(0, s, dtype="int32").unsqueeze(0)
        hidden = self.wte(input_ids) + self.wpe(pos)
        hidden = self.drop(hidden)
        for blk in self.h:
            hidden = blk(hidden)
        return self.ln_f(hidden)

    def forward_prefill(self, input_ids, s_max):
        """Dense prompt pass that also fills the decode KV caches.

        Returns (hidden [B, S, E], caches [L, 2, B, H, s_max, D]).
        """
        import paddle_tpu as paddle
        from .. import ops
        b, s = input_ids.shape
        if s > s_max:
            raise ValueError(f"prompt length {s} exceeds cache size {s_max}")
        pos = ops.arange(0, s, dtype="int32").unsqueeze(0)
        hidden = self.drop(self.wte(input_ids) + self.wpe(pos))
        h, d = self.config.num_attention_heads, self.config.head_dim
        pad = (paddle.zeros([b, h, s_max - s, d],
                            dtype=self.config.dtype)
               if s < s_max else None)
        caches = []
        for blk in self.h:
            hidden, k, v = blk.forward_kv(hidden)
            if pad is not None:
                k = ops.concat([k, pad.astype(k.dtype)], axis=2)
                v = ops.concat([v, pad.astype(v.dtype)], axis=2)
            caches.append(ops.stack([k, v]))
        return self.ln_f(hidden), ops.stack(caches)


class GPT2ForCausalLM(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.config = config
        self.transformer = GPT2Model(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  weight_attr=I.Normal(
                                      std=config.initializer_range),
                                  bias_attr=False)

    def forward(self, input_ids, labels=None):
        hidden = self.transformer(input_ids)
        logits = self._logits(hidden)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]).astype("float32"),
            labels.reshape([-1]))
        return logits, loss

    def _logits(self, hidden):
        if self.lm_head is None:
            from .. import ops
            return ops.matmul(hidden, self.transformer.wte.weight,
                              transpose_y=True)
        return self.lm_head(hidden)

    def prefill(self, input_ids, s_max, n_valid=None):
        """Prompt pass for incremental decode (the serving path).

        Returns (last_logits [B, 1, V], caches [L, 2, B, H, s_max, D],
        t [B, 1] int32 — the next write position).

        ``n_valid`` ([B, 1] int32) marks the true prompt length when
        ``input_ids`` is right-padded onto a bucket ladder: the last-token
        hidden state is gathered at position n_valid-1 (a dynamic gather,
        so ONE executable per bucket serves every prompt length) and decode
        resumes at t = n_valid, overwriting the pad rows of the cache
        before any step can attend them.
        """
        import paddle_tpu as paddle
        b, s = input_ids.shape
        hidden, caches = self.transformer.forward_prefill(input_ids, s_max)
        if n_valid is None:
            last = hidden[:, s - 1:s]
            t = paddle.full([b, 1], s, dtype="int32")
        else:
            from .. import ops
            idx = (n_valid - 1).astype("int32").reshape([b, 1, 1])
            last = ops.take_along_axis(hidden, idx, axis=1)
            t = n_valid.astype("int32")
        logits = self._logits(last)
        return logits, caches, t

    def decode_step(self, tok, caches, t):
        """One incremental token through every layer's KV cache.

        tok: [B, 1] int; caches: [L, 2, B, H, S_max, D]; t: [B, 1] int32.
        All shapes are static, so `jit.to_static(model.decode_step)`
        compiles ONE executable that serves every step — the XLA analog of
        the reference's fused decode kernels
        (masked_multihead_attention_kernel.cu). Returns
        (logits [B, 1, V], caches', t+1).
        """
        from .. import ops
        hidden = self.transformer.wte(tok) + self.transformer.wpe(t)
        hidden = self.transformer.drop(hidden)
        new_caches = []
        for i, blk in enumerate(self.transformer.h):
            hidden, nc = blk.decode(hidden, caches[i], t)
            new_caches.append(nc)
        hidden = self.transformer.ln_f(hidden)
        return self._logits(hidden), ops.stack(new_caches), t + 1

    # -- paged-KV serving route (vLLM-style block cache) --------------------

    def paged_alloc(self, n_pages, block_size=64, cache_dtype=None):
        """Allocate the physical KV page pool: per layer, (kc, vc) of
        [n_pages, H, block_size, D]. Pages are position-free storage —
        a block table maps (sequence, logical block) -> pool row, so the
        same pool serves many sequences of different lengths. After
        calibrate_cachekv_int8 the pools allocate int8; cache_dtype
        overrides explicitly (dynamic-quant callers)."""
        import paddle_tpu as paddle
        cfg = self.config
        h, d = cfg.num_attention_heads, cfg.head_dim
        dtype = cache_dtype or (
            "int8" if self._cachekv_scales is not None else cfg.dtype)
        return [(paddle.zeros([n_pages, h, block_size, d], dtype=dtype),
                 paddle.zeros([n_pages, h, block_size, d], dtype=dtype))
                for _ in range(cfg.num_hidden_layers)]

    _cachekv_scales = None

    def calibrate_cachekv_int8(self, sample_ids):
        """Static per-head int8 cache scales from a calibration batch
        (reference cache_k_quant_scales, static mode) — mirrors the Llama
        API; see _cachekv_scales_from. Pass None to disable."""
        if sample_ids is None:
            self._cachekv_scales = None
            return None
        import paddle_tpu as paddle
        b, s = sample_ids.shape
        with paddle.no_grad():
            _, caches, _ = self.prefill(sample_ids, s)
        self._cachekv_scales = _cachekv_scales_from(caches._data)
        return self._cachekv_scales

    def paged_prefill_into(self, input_ids, layers, block_tables,
                           block_size=64, dec_base=None, logits_at=None,
                           dynamic_cache_scales=False, cache_scales=None,
                           dynamic_scale_valid=None, logits_all=False):
        """Prompt pass writing KV into a CALLER-OWNED page pool.

        input_ids [B, s]; layers: ``paged_alloc`` pool; block_tables
        [B, blocks_per_seq] int32 rows naming each sequence's pages.
        Returns (last_logits [B, V], new_layers). This is the admission
        primitive continuous batchers use: the pool persists across
        requests, only the named pages are written.

        dec_base [B] int32 (optional): CHUNKED-prefill mode — this call
        appends s tokens after an existing prefix of dec_base rows
        (multi-token decode-mode append: pos = dec_base + local, causal
        within the chunk, attending the whole prefix). A fixed chunk
        width makes prompt processing reuse ONE executable for every
        prompt length instead of compiling per length.

        Dynamic cachekv-int8 x chunked composition (reference analog:
        block_multihead_attention takes cache quant scales AND chunked
        input in one op): dynamic_cache_scales=True computes per-
        (sequence, head) scales from this call (the FIRST chunk /
        unchunked prompt; dynamic_scale_valid [B] masks a pad tail out
        of the statistics) and returns them third; cache_scales (the
        per-layer scale dicts a first chunk returned) makes LATER chunks
        quantize with those same scales, so the whole chunk loop is
        bit-consistent with a single-call prefill given the same scales.
        """
        import paddle_tpu as paddle
        from ..incubate.nn.functional.decode_attention import \
            block_multihead_attention

        if dynamic_cache_scales and cache_scales is not None:
            raise ValueError("dynamic_cache_scales computes scales; "
                             "cache_scales consumes them — pass one")
        b, s = input_ids.shape
        bt = block_tables
        if dec_base is None:
            enc = paddle.to_tensor(np.full((b,), s, np.int32))
            dec = paddle.to_tensor(np.zeros((b,), np.int32))
            pos_row = paddle.to_tensor(
                np.tile(np.arange(s, dtype=np.int32), (b, 1)))
        else:
            enc = paddle.to_tensor(np.zeros((b,), np.int32))
            dec = dec_base
            pos_row = dec_base.reshape([b, 1]) + paddle.to_tensor(
                np.arange(s, dtype=np.int32)).reshape([1, s])
            # chunked pad rows can run past the position table when slot
            # capacity (blocks_per_seq*block_size) exceeds
            # max_position_embeddings; clamp EXPLICITLY — pad rows are
            # masked/overwritten before any bounded read, but the safety
            # must not hang on jnp's silent gather clamping (ADVICE r3)
            pos_row = paddle.clip(
                pos_row, 0, self.config.max_position_embeddings - 1)
        cu_q = paddle.to_tensor(np.arange(b + 1, dtype=np.int32) * s)

        # packed-token forward: hidden is [T, E] (sequences concatenated)
        ids_flat = input_ids.reshape([b * s])
        pos_flat = pos_row.reshape([b * s])
        hidden = self.transformer.wte(ids_flat) + self.transformer.wpe(
            pos_flat)
        hidden = self.transformer.drop(hidden)
        this = paddle.to_tensor(np.full((b,), s, np.int32))
        layers_state = []
        scales_out = [] if dynamic_cache_scales else None
        for li, (blk, (kc, vc)) in enumerate(zip(self.transformer.h,
                                                 layers)):
            x = blk.ln_1(hidden)
            qkv = blk.attn.c_attn(x)                     # [T, 3*H*D]
            if dynamic_cache_scales:
                extra = dict(use_dynamic_cachekv_quant=True,
                             compute_dynamic_scales=True,
                             dynamic_scale_valid=dynamic_scale_valid)
            else:
                extra = _cache_scale_kwargs(
                    cache_scales if cache_scales is not None
                    else self._cachekv_scales, li)
            res = block_multihead_attention(
                qkv, kc, vc, enc, dec, this, None, None, cu_q, cu_q,
                bt, block_size=block_size, **extra)
            if dynamic_cache_scales:
                out, _, kc, vc, (kq, vq, kdq, vdq) = res
                scales_out.append({"kq": kq, "vq": vq,
                                   "kdq": kdq, "vdq": vdq})
            else:
                out, _, kc, vc = res
            hidden = hidden + blk.attn.resid_dropout(blk.attn.c_proj(out))
            hidden = hidden + blk.mlp(blk.ln_2(hidden))
            layers_state.append((kc, vc))
        hidden = self.transformer.ln_f(hidden)
        h3 = hidden.reshape([b, s, -1])
        if logits_all:
            # speculative verify: the target scores EVERY appended
            # position in one pass (s = draft_k + 1, so the full lm
            # head over s positions is the point, not a waste)
            logits = self._logits(h3)                    # [b, s, V]
        elif logits_at is not None:
            # chunked prefill: project ONLY the requested position (the
            # lm head over all C positions would be C x the needed FLOPs)
            oh = F.one_hot(logits_at.reshape([b]).astype("int64"),
                           s).astype(h3.dtype)
            logits = self._logits(paddle.einsum("bs,bse->be", oh, h3))
        else:
            logits = self._logits(h3[:, s - 1])
        if dynamic_cache_scales:
            return logits, layers_state, scales_out
        return logits, layers_state

    @staticmethod
    def _paged_state(layers_state, bt, b, s, block_size, blocks_per_seq):
        """The SHARED paged-decode state convention (GPT-2 and Llama build
        identical dicts, so one batcher / one compiled-step recipe serves
        both families)."""
        import paddle_tpu as paddle
        return {"layers": layers_state, "block_tables": bt,
                "dec_lens": paddle.to_tensor(np.full((b,), s, np.int32)),
                "block_size": block_size,
                "capacity": blocks_per_seq * block_size,
                # per-step constants (batch-size-only): built once, not on
                # the hot decode path
                "zeros_b": paddle.to_tensor(np.zeros((b,), np.int32)),
                "ones_b": paddle.to_tensor(np.ones((b,), np.int32)),
                "cu_b": paddle.to_tensor(np.arange(b + 1, dtype=np.int32))}

    @staticmethod
    def _paged_prefill_impl(model, input_ids, block_size, blocks_per_seq):
        """Shared fresh-pool prefill: allocate pages, identity block table,
        run the model's pool-writing prefill, wrap the state dict."""
        import paddle_tpu as paddle
        cfg = model.config
        b, s = input_ids.shape
        if blocks_per_seq is None:
            blocks_per_seq = (cfg.max_position_embeddings + block_size - 1) \
                // block_size
        n_blocks = b * blocks_per_seq
        bt = paddle.to_tensor(
            np.arange(n_blocks, dtype=np.int32).reshape(b, blocks_per_seq))
        layers = model.paged_alloc(n_blocks, block_size)
        logits, layers_state = model.paged_prefill_into(
            input_ids, layers, bt, block_size)
        return logits, GPT2ForCausalLM._paged_state(
            layers_state, bt, b, s, block_size, blocks_per_seq)

    @staticmethod
    def _paged_generate_loop(model, input_ids, max_new_tokens, block_size,
                             blocks_per_seq, decode_fn):
        """Shared greedy paged-decode driver (capacity validation + the
        prefill/step loop), parameterized the way _generate_loop and
        _beam_loop are."""
        from .. import ops
        b, s = input_ids.shape
        needed = s + max_new_tokens
        if needed > model.config.max_position_embeddings:
            # silent-clip hazard: position tables and the block table would
            # both clip-index and corrupt live pages
            raise ValueError(
                f"prompt {s} + {max_new_tokens} new tokens exceeds "
                f"max_position_embeddings="
                f"{model.config.max_position_embeddings}")
        if blocks_per_seq is None:
            # size the page pool to the actual timeline, not the model max
            blocks_per_seq = (needed + block_size - 1) // block_size
        elif needed > blocks_per_seq * block_size:
            raise ValueError(
                f"paged cache capacity {blocks_per_seq * block_size} too "
                f"small for prompt {s} + {max_new_tokens} new tokens")
        logits, state = model.paged_prefill(input_ids, block_size,
                                            blocks_per_seq)
        step = decode_fn if decode_fn is not None else model.paged_decode_step
        toks = [input_ids]
        tok = ops.argmax(logits, axis=-1).reshape([b])
        for i in range(max_new_tokens):
            toks.append(tok.reshape([b, 1]))
            if i + 1 == max_new_tokens:
                break
            logits, state = step(tok.astype(input_ids.dtype), state)
            tok = ops.argmax(logits, axis=-1).reshape([b])
        return ops.concat([x.astype("int64") for x in toks], axis=1)

    @staticmethod
    def _speculative_loop(target, draft, input_ids, max_new_tokens,
                          draft_k, block_size, eos_id, compile,
                          return_stats):
        """Greedy speculative decoding over the paged cache (beyond the
        reference, which has no in-tree speculative decoding; the serving
        analog is the draft/verify split in modern engines).

        The cheap DRAFT model proposes ``draft_k`` tokens autoregressively;
        the TARGET scores all proposals in ONE forward (paged_prefill_into
        with logits_all=True) and accepts the longest prefix matching its
        own greedy choices, plus its correction token — so each target
        dispatch yields 1..draft_k+1 tokens, and the output is EXACTLY the
        target's greedy sequence. Rollback after a rejection is free by
        construction: the host owns ``dec_lens``, bounded attention never
        reads rows past it, and stale rows are overwritten on the next
        append. Works across families (any draft/target pair sharing a
        vocab — both implement the shared paged-state convention)."""
        import paddle_tpu as paddle
        from .. import ops

        if input_ids.shape[0] != 1:
            raise ValueError("speculative decoding is single-sequence "
                             "(batch it at the serving layer)")
        if draft_k < 1:
            raise ValueError("draft_k must be >= 1")
        if draft.config.vocab_size != target.config.vocab_size:
            raise ValueError(
                f"draft vocab {draft.config.vocab_size} != target vocab "
                f"{target.config.vocab_size}")
        _, L = input_ids.shape
        if max_new_tokens <= 0:
            # generate(ids, 0) returns the prompt unchanged — match it
            out = paddle.to_tensor(
                np.asarray(input_ids._data).astype(np.int64))
            if not return_stats:
                return out
            return out, {"rounds": 0, "proposed": 0, "matched": 0,
                         "acceptance_rate": 0.0,
                         "tokens_per_target_dispatch": 0.0}
        needed = L + max_new_tokens
        for m, who in ((target, "target"), (draft, "draft")):
            if needed > m.config.max_position_embeddings:
                raise ValueError(
                    f"prompt {L} + {max_new_tokens} exceeds the {who}'s "
                    f"max_position_embeddings="
                    f"{m.config.max_position_embeddings}")
        bps = (needed + block_size - 1) // block_size

        with paddle.no_grad():
            t_logits, t_state = target.paged_prefill(input_ids, block_size,
                                                     bps)
            d_logits, d_state = draft.paged_prefill(input_ids, block_size,
                                                    bps)
        def _verify_body(ids, layers, bt, dec):
            return target.paged_prefill_into(
                ids, layers, bt, block_size, dec_base=dec,
                logits_all=True)

        def _catchup_body(ids, layers, bt, dec, at):
            # variable-length draft append (1 token after a rejection, 2
            # after a fully-accepted round — see d_rows below); returns
            # the LAST position's logits, i.e. the first proposal
            return draft.paged_prefill_into(
                ids, layers, bt, block_size, dec_base=dec, logits_at=at)

        if compile:
            from .. import jit
            t_step = jit.to_static(target.paged_decode_step,
                                   donate_args=(1,))
            d_step = jit.to_static(draft.paged_decode_step,
                                   donate_args=(1,))
            verify = jit.to_static(_verify_body, donate_args=(1,))
            catchup = jit.to_static(_catchup_body, donate_args=(1,))
        else:
            t_step, d_step = target.paged_decode_step, draft.paged_decode_step
            verify, catchup = _verify_body, _catchup_body

        # invariants: the TARGET cache holds rows for prompt +
        # accepted[:-1] (``accepted[-1]`` is pending, the next input);
        # the DRAFT cache holds correct rows for the first ``d_rows``
        # positions of prompt + accepted — after a fully-accepted round
        # it runs one short (the last proposal was never fed back), so
        # each round starts by appending accepted[d_rows - L:] to the
        # draft (1 token after a rejection, 2 after a full accept),
        # whose last-position logits ARE the first proposal.
        accepted = [int(np.asarray(t_logits._data)[0].argmax())]
        d_rows = L
        rounds = proposed = matched = 0
        with paddle.no_grad():
            while True:
                if eos_id is not None and eos_id in accepted:
                    accepted = accepted[:accepted.index(eos_id) + 1]
                    break
                remaining = max_new_tokens - len(accepted)
                if remaining <= 0:
                    break
                valid = L + len(accepted) - 1
                k = min(draft_k, remaining - 1)
                if k == 0:
                    # budget for exactly one more: plain target step
                    t_state["dec_lens"] = paddle.to_tensor(
                        np.array([valid], np.int32))
                    lg, t_state = t_step(paddle.to_tensor(
                        np.array([accepted[-1]], np.int64)), t_state)
                    accepted.append(int(np.asarray(lg._data)[0].argmax()))
                    continue
                # draft catch-up append ending at pending -> proposal 1
                cu = accepted[d_rows - L:]
                dl, d_state["layers"] = catchup(
                    paddle.to_tensor(np.array([cu], np.int64)),
                    d_state["layers"], d_state["block_tables"],
                    paddle.to_tensor(np.array([d_rows], np.int32)),
                    paddle.to_tensor(np.array([len(cu) - 1], np.int32)))
                d_rows += len(cu)
                tok = int(np.asarray(dl._data)[0].argmax())
                props = [tok]
                # k-1 single draft steps propose the rest
                d_state["dec_lens"] = paddle.to_tensor(
                    np.array([d_rows], np.int32))
                for _ in range(k - 1):
                    dl, d_state = d_step(paddle.to_tensor(
                        np.array([tok], np.int64)), d_state)
                    tok = int(np.asarray(dl._data)[0].argmax())
                    props.append(tok)
                d_rows += k - 1              # rows for props[:k-1] inputs
                # target scores pending + all k proposals in one pass
                ids_v = paddle.to_tensor(
                    np.array([[accepted[-1]] + props], np.int64))
                vlogits, t_state["layers"] = verify(
                    ids_v, t_state["layers"], t_state["block_tables"],
                    paddle.to_tensor(np.array([valid], np.int32)))
                g = np.asarray(vlogits._data)[0].argmax(-1)   # [k+1]
                j = 0
                while j < k and props[j] == int(g[j]):
                    j += 1
                accepted += props[:j] + [int(g[j])]
                rounds += 1
                proposed += k
                matched += j
                # draft rows correct through prompt + accepted[:-1] at
                # most (rejected proposals' rows are stale); a full
                # accept leaves it one short of even that
                d_rows = min(d_rows, L + len(accepted) - 1)
        if eos_id is not None and eos_id in accepted:
            accepted = accepted[:accepted.index(eos_id) + 1]
        out = paddle.to_tensor(np.concatenate(
            [np.asarray(input_ids._data).reshape(-1),
             np.asarray(accepted, np.int64)])[None])
        if not return_stats:
            return out
        return out, {
            "rounds": rounds, "proposed": proposed, "matched": matched,
            "acceptance_rate": matched / max(proposed, 1),
            "tokens_per_target_dispatch":
                len(accepted) / max(rounds, 1) if rounds else 1.0,
        }

    def generate_paged_speculative(self, input_ids, max_new_tokens,
                                   draft_model, draft_k=4, block_size=64,
                                   eos_id=None, compile=True,
                                   return_stats=False):
        """Greedy speculative decoding: ``draft_model`` proposes
        ``draft_k`` tokens per round, this model verifies them in one
        forward — token-exact vs ``generate``/``generate_paged`` while
        spending 1 target dispatch per 1..draft_k+1 accepted tokens (the
        dispatch-latency lever, complementary to decode_block which
        amortizes dispatches without a draft). See _speculative_loop."""
        return self._speculative_loop(self, draft_model, input_ids,
                                      max_new_tokens, draft_k, block_size,
                                      eos_id, compile, return_stats)

    def paged_prefill(self, input_ids, block_size=64, blocks_per_seq=None):
        """Prompt pass through the paged block cache
        (block_multihead_attention, reference
        incubate/nn/functional/block_multihead_attention.py:19).

        Returns (last_logits [B, V], state dict). The cache is a pool of
        physical [block_size] pages per layer; block_tables maps each
        sequence's logical block index to its page — decode appends into
        pages instead of one dense [B, S_max] strip, so cache memory
        scales with actual lengths and pages are shareable/evictable.
        """
        return self._paged_prefill_impl(self, input_ids, block_size,
                                        blocks_per_seq)

    def paged_decode_step(self, tok, state):
        """One token per sequence through the paged cache (decode mode:
        seq_lens_this_time == 1, append at dec_lens). tok: [B]."""
        import paddle_tpu as paddle
        from ..incubate.nn.functional.decode_attention import \
            block_multihead_attention

        cfg = self.config
        b = tok.shape[0]
        t = state["dec_lens"]
        bt = state["block_tables"]
        enc, this, cu_q = state["zeros_b"], state["ones_b"], state["cu_b"]
        hidden = self.transformer.wte(tok) + self.transformer.wpe(t)
        hidden = self.transformer.drop(hidden)
        dyn = state.get("cache_scales")
        new_layers = []
        for li, (blk, (kc, vc)) in enumerate(zip(self.transformer.h,
                                                 state["layers"])):
            x = blk.ln_1(hidden)
            qkv = blk.attn.c_attn(x)                     # [B, 3*H*D]
            if dyn is not None:
                # per-(slot, head) scales ride the state (dynamic int8)
                kwargs = dict(_cache_scale_kwargs(dyn, li),
                              use_dynamic_cachekv_quant=True)
            else:
                kwargs = _cache_scale_kwargs(self._cachekv_scales, li)
            out, _, kc, vc = block_multihead_attention(
                qkv, kc, vc, enc, t, this, None, None, cu_q, cu_q, bt,
                block_size=state["block_size"], **kwargs)
            hidden = hidden + blk.attn.resid_dropout(blk.attn.c_proj(out))
            hidden = hidden + blk.mlp(blk.ln_2(hidden))
            new_layers.append((kc, vc))
        hidden = self.transformer.ln_f(hidden)
        logits = self._logits(hidden)
        new_state = dict(state, layers=new_layers, dec_lens=t + 1)
        return logits, new_state

    def generate_paged(self, input_ids, max_new_tokens, block_size=64,
                       blocks_per_seq=None, decode_fn=None):
        """Greedy decode over the paged block cache (the serving route the
        reference exposes as block_multihead_attention + AnalysisPredictor;
        here the cache pages live in HBM and XLA compiles the step).

        decode_fn: optionally ``jit.to_static(model.paged_decode_step)`` —
        the state pytree has static shapes, so one executable serves every
        step here too."""
        return self._paged_generate_loop(self, input_ids, max_new_tokens,
                                         block_size, blocks_per_seq,
                                         decode_fn)

    def paged_fused_step(self, tok, chunk_ids, chunk_bt, chunk_dec,
                         chunk_at, state):
        """ONE packed call advancing every decode slot AND one admission
        chunk (vLLM unified scheduling; see the Llama twin's docstring
        for the layout). Returns (decode_logits [B, V], chunk_logits
        [1, V], new_state)."""
        import paddle_tpu as paddle
        from .. import ops
        from ..incubate.nn.functional.decode_attention import \
            block_multihead_attention

        b = tok.shape[0]
        c = chunk_ids.shape[0]
        t = state["dec_lens"]
        bt = ops.concat([state["block_tables"], chunk_bt], axis=0)
        enc = paddle.to_tensor(np.zeros((b + 1,), np.int32))
        this = paddle.to_tensor(
            np.concatenate([np.ones((b,), np.int32), [c]]).astype(np.int32))
        dec_call = ops.concat([t, chunk_dec], axis=0)
        cu_q = paddle.to_tensor(np.concatenate(
            [np.arange(b + 1, dtype=np.int32), [b + c]]).astype(np.int32))
        if state.get("cache_scales") is not None:
            raise NotImplementedError(
                "fused admission + dynamic cachekv quant: use static "
                "calibration (calibrate_cachekv_int8)")

        all_tok = ops.concat([tok.reshape([b]), chunk_ids.reshape([c])],
                             axis=0)
        # positions: decode rows at t, chunk rows at chunk_dec + local
        pos = ops.concat([t.reshape([b]),
                          (chunk_dec.reshape([1]) + paddle.to_tensor(
                              np.arange(c, dtype=np.int32))).reshape([c])],
                         axis=0)
        hidden = self.transformer.wte(all_tok) + self.transformer.wpe(pos)
        hidden = self.transformer.drop(hidden)
        new_layers = []
        for li, (blk, (kc, vc)) in enumerate(zip(self.transformer.h,
                                                 state["layers"])):
            x = blk.ln_1(hidden)
            qkv = blk.attn.c_attn(x)                     # [B+C, 3*H*D]
            out, _, kc, vc = block_multihead_attention(
                qkv, kc, vc, enc, dec_call, this, None, None, cu_q, cu_q,
                bt, block_size=state["block_size"],
                **_cache_scale_kwargs(self._cachekv_scales, li))
            hidden = hidden + blk.attn.resid_dropout(blk.attn.c_proj(out))
            hidden = hidden + blk.mlp(blk.ln_2(hidden))
            new_layers.append((kc, vc))
        hidden = self.transformer.ln_f(hidden)
        dec_logits = self._logits(hidden[:b])            # [B, V]
        chunk_h = hidden[b:]                             # [C, E]
        oh = F.one_hot(chunk_at.reshape([1]).astype("int64"),
                       c).astype(chunk_h.dtype)
        chunk_logits = self._logits(
            paddle.einsum("oc,ce->oe", oh, chunk_h))     # [1, V]
        new_state = dict(state, layers=new_layers, dec_lens=t + 1)
        return dec_logits, chunk_logits, new_state

    @staticmethod
    def _select_token(logits_np, do_sample, temperature, top_k, top_p, rng):
        """Next-token selection on host logits [B, V] (reference surface:
        generation_utils' TopKProcess/TopPProcess + sampling).

        Greedy unless do_sample; sampling applies temperature, then top-k
        truncation, then nucleus (top-p) truncation, then draws from the
        renormalized distribution."""
        if not do_sample:
            return logits_np.argmax(-1)
        logits = logits_np.astype(np.float64) / max(temperature, 1e-6)
        out = np.empty(logits.shape[0], np.int64)
        for b in range(logits.shape[0]):
            row = logits[b]
            if top_k and 0 < top_k < row.shape[-1]:
                kth = np.partition(row, -top_k)[-top_k]
                row = np.where(row < kth, -np.inf, row)
            probs = np.exp(row - row.max())
            probs /= probs.sum()
            if top_p is not None and 0 < top_p < 1.0:
                order = np.argsort(-probs)
                csum = np.cumsum(probs[order])
                # keep the smallest prefix reaching top_p (always >= 1)
                cutoff = int(np.searchsorted(csum, top_p) + 1)
                keep = order[:cutoff]
                mask = np.zeros_like(probs, bool)
                mask[keep] = True
                probs = np.where(mask, probs, 0.0)
                probs /= probs.sum()
            out[b] = rng.choice(probs.shape[-1], p=probs)
        return out

    @staticmethod
    def _generate_loop(prefill_fn, step_fn, input_ids, max_new_tokens,
                       do_sample, temperature, top_k, top_p, seed,
                       eos_id=None, pad_id=None):
        """Shared incremental-decode driver (GPT-2 and Llama): prefill,
        then step/pick until the budget, with greedy selection staying on
        device and sampling reading logits to host.

        eos_id: per-row early stop (reference generation_utils'
        eos_token_id semantics) — once a row emits EOS, its later
        positions emit ``pad_id`` (default: eos_id) and the loop exits
        as soon as EVERY row has finished. The finished test is the one
        host sync per step; greedy decoding without eos_id stays fully
        on device.

        NOTE on the hot path: each step's returned caches are fresh
        buffers (functional update); true in-place reuse needs donation
        support in StaticFunction — tracked for the serving tier."""
        import paddle_tpu as paddle
        from .. import ops
        b = input_ids.shape[0]
        rng = np.random.RandomState(seed)
        if pad_id is None:
            pad_id = eos_id
        done = np.zeros((b,), bool)

        def pick(lg):
            if not do_sample:
                # greedy stays ON DEVICE: no host round trip per step
                return ops.argmax(lg[:, -1], axis=-1).reshape([b, 1])
            sel = GPT2ForCausalLM._select_token(
                np.asarray(lg._data)[:, -1], True, temperature, top_k,
                top_p, rng)
            return paddle.to_tensor(sel.reshape(b, 1))

        def apply_eos(tok):
            """Mask finished rows to pad and fold this step's EOS hits
            into `done` (host-side: the mask drives python control flow)."""
            tok_np = np.asarray(tok._data).reshape(b)
            out = np.where(done, pad_id, tok_np)
            done[:] = done | (out == eos_id)
            return paddle.to_tensor(out.reshape(b, 1))

        logits, caches, t = prefill_fn()
        toks = [input_ids]
        tok = pick(logits)
        if eos_id is not None:
            tok = apply_eos(tok)
        for i in range(max_new_tokens):
            toks.append(tok)
            if i + 1 == max_new_tokens or (eos_id is not None
                                           and bool(done.all())):
                break
            logits, caches, t = step_fn(tok.astype(input_ids.dtype),
                                        caches, t)
            tok = pick(logits)
            if eos_id is not None:
                tok = apply_eos(tok)
        out = ops.concat([x.astype("int64") for x in toks], axis=1)
        if eos_id is not None and len(toks) - 1 < max_new_tokens:
            # every row finished early: right-pad to the requested length
            # so the output shape stays [B, S + max_new_tokens]
            short = max_new_tokens - (len(toks) - 1)
            pad = paddle.to_tensor(
                np.full((b, short), pad_id, np.int64))
            out = ops.concat([out, pad], axis=1)
        return out

    @staticmethod
    def _resolve_s_max(config, s, max_new_tokens, s_max):
        """Default + validate the cache size (shared by every generate
        flavor in both model families): positions past the embedding
        table would CLIP silently (jnp.take), so reject loudly."""
        if s_max is None:
            s_max = min(config.max_position_embeddings, s + max_new_tokens)
        if s_max > config.max_position_embeddings:
            raise ValueError(
                f"s_max={s_max} exceeds max_position_embeddings="
                f"{config.max_position_embeddings}")
        if s + max_new_tokens > s_max:
            raise ValueError(f"s_max={s_max} too small for prompt {s} + "
                             f"{max_new_tokens} new tokens")
        return s_max

    @staticmethod
    def _beam_loop(prefill_fn, step_fn, input_ids, max_new_tokens,
                   num_beams, length_penalty):
        """Shared beam-search driver over the KV cache.

        Beams ride the batch dimension: inputs expand to B*W rows, the
        per-beam caches reorder by index_select along the cache's batch
        axis at every step (the KV-cache beam shuffle the reference's
        beam_search_decode does), and ONE decode executable at batch B*W
        serves every step. No EOS handling — fixed-length beams; the best
        beam per batch wins by summed log-prob / len**length_penalty.
        """
        import paddle_tpu as paddle
        from .. import ops
        b, s = input_ids.shape
        w = num_beams
        ids_np = np.asarray(input_ids._data)
        # prefill ONCE at batch B, then fan the caches out to B*W rows —
        # the W beams of a batch share the prompt's KV exactly
        logits, caches, t = prefill_fn(input_ids)

        def logprobs(lg):
            x = np.asarray(lg._data)[:, -1].astype(np.float64)
            x = x - x.max(-1, keepdims=True)
            return x - np.log(np.exp(x).sum(-1, keepdims=True))

        v = logits.shape[-1]
        if w > v:
            raise ValueError(f"num_beams={w} exceeds vocab_size={v}: the "
                             f"seed step cannot pick {w} distinct tokens")
        rep = paddle.to_tensor(np.repeat(np.arange(b, dtype=np.int64), w))
        caches = ops.index_select(caches, rep, axis=2)
        t = ops.index_select(t, rep, axis=0)
        # seed: the W beams of each batch start DISTINCT (top-W tokens of
        # the prompt's next-token distribution)
        lp0 = logprobs(logits)                            # [B, V]
        top0 = np.argsort(-lp0, axis=-1)[:, :w]           # [B, W]
        beam_scores = np.take_along_axis(lp0, top0, -1)   # [B, W]
        beam_tokens = [top0.reshape(b * w, 1)]            # list of [BW, 1]
        tok = paddle.to_tensor(beam_tokens[0])
        for i in range(1, max_new_tokens):
            logits, caches, t = step_fn(
                tok.astype(input_ids.dtype), caches, t)
            lp = logprobs(logits).reshape(b, w, v)        # [B, W, V]
            total = beam_scores[..., None] + lp           # [B, W, V]
            flat = total.reshape(b, w * v)
            best = np.argsort(-flat, axis=-1)[:, :w]      # [B, W]
            src_beam = best // v                          # [B, W]
            token = best % v                              # [B, W]
            beam_scores = np.take_along_axis(flat, best, -1)
            # reorder every beam-carrying structure by the source beams
            gather = (np.arange(b)[:, None] * w + src_beam).reshape(-1)
            gidx = paddle.to_tensor(gather.astype(np.int64))
            caches = ops.index_select(caches, gidx, axis=2)
            t = ops.index_select(t, gidx, axis=0)
            beam_tokens = [tk[gather] for tk in beam_tokens]
            beam_tokens.append(token.reshape(b * w, 1))
            tok = paddle.to_tensor(beam_tokens[-1])
        # best beam per batch (length fixed, penalty kept for API parity)
        denom = max_new_tokens ** length_penalty if length_penalty else 1.0
        best_beam = (beam_scores / denom).argmax(-1)      # [B]
        rows = np.arange(b) * w + best_beam
        gen = np.concatenate([tk[rows] for tk in beam_tokens], axis=1)
        return paddle.to_tensor(
            np.concatenate([ids_np.astype(np.int64), gen], axis=1))

    def generate_beam(self, input_ids, max_new_tokens, num_beams=4,
                      s_max=None, decode_fn=None, length_penalty=0.0):
        """Beam search over the KV cache (reference generation's
        beam_search mode). Returns the best beam per batch,
        [B, S + max_new_tokens]."""
        _, s = input_ids.shape
        s_max = self._resolve_s_max(self.config, s, max_new_tokens, s_max)
        step = decode_fn if decode_fn is not None else self.decode_step
        return self._beam_loop(lambda ids: self.prefill(ids, s_max), step,
                               input_ids, max_new_tokens, num_beams,
                               length_penalty)

    def generate(self, input_ids, max_new_tokens, s_max=None,
                 decode_fn=None, do_sample=False, temperature=1.0,
                 top_k=0, top_p=None, seed=None, eos_id=None, pad_id=None):
        """Incremental decode over the KV cache — greedy by default;
        ``do_sample=True`` draws with temperature / top-k / top-p
        (nucleus) truncation, seeded via ``seed`` for reproducibility.
        ``eos_id`` stops each row at its end-of-sequence token (later
        positions emit ``pad_id``, default eos_id) and ends the loop
        early once every row is done; output shape stays
        [B, S + max_new_tokens].

        decode_fn: optionally a compiled decode step (e.g.
        ``jit.to_static(model.decode_step)``) so every token reuses one
        executable; defaults to the eager step. Returns [B, S + new] ids.
        """
        import paddle_tpu as paddle
        from .. import ops
        _, s = input_ids.shape
        s_max = self._resolve_s_max(self.config, s, max_new_tokens, s_max)
        step = decode_fn if decode_fn is not None else self.decode_step
        return self._generate_loop(
            lambda: self.prefill(input_ids, s_max), step, input_ids,
            max_new_tokens, do_sample, temperature, top_k, top_p, seed,
            eos_id=eos_id, pad_id=pad_id)

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in self.parameters())
