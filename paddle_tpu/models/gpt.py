"""GPT-2 model family (BASELINE.md config #2, GPT-2 124M compiled-path bench).

Reference fixture: test/auto_parallel/get_gpt_model.py and the fused
transformer tier (phi/kernels/fusion). TPU-first: pre-norm blocks, learned
positional embeddings, GELU MLP, attention through the fused SDPA path.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..nn.common import Dropout, Embedding, Linear
from ..nn.norm import LayerNorm


@dataclass
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    dropout: float = 0.1
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


def gpt2_124m_config(**overrides) -> GPT2Config:
    cfg = GPT2Config()
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


class GPT2Attention(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        init = I.Normal(std=config.initializer_range)
        self.c_attn = Linear(config.hidden_size, 3 * config.hidden_size,
                             weight_attr=init)
        self.c_proj = Linear(config.hidden_size, config.hidden_size,
                             weight_attr=init)
        self.config = config
        self.resid_dropout = Dropout(config.dropout)

    def forward(self, hidden):
        b, s, _ = hidden.shape
        h, d = self.config.num_attention_heads, self.config.head_dim
        qkv = self.c_attn(hidden).reshape([b, s, 3, h, d])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=self.config.dropout if self.training else 0.0)
        out = self.c_proj(out.reshape([b, s, h * d]))
        return self.resid_dropout(out)


class GPT2MLP(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        init = I.Normal(std=config.initializer_range)
        self.c_fc = Linear(config.hidden_size, config.intermediate_size,
                           weight_attr=init)
        self.c_proj = Linear(config.intermediate_size, config.hidden_size,
                             weight_attr=init)
        self.dropout = Dropout(config.dropout)

    def forward(self, x):
        return self.dropout(self.c_proj(F.gelu(self.c_fc(x), approximate=True)))


class GPT2Block(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.attn = GPT2Attention(config)
        self.ln_2 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.mlp = GPT2MLP(config)

    def forward(self, hidden):
        hidden = hidden + self.attn(self.ln_1(hidden))
        return hidden + self.mlp(self.ln_2(hidden))


class GPT2Model(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.config = config
        init = I.Normal(std=config.initializer_range)
        self.wte = Embedding(config.vocab_size, config.hidden_size,
                             weight_attr=init)
        self.wpe = Embedding(config.max_position_embeddings,
                             config.hidden_size, weight_attr=init)
        self.drop = Dropout(config.dropout)
        self.h = [GPT2Block(config) for _ in range(config.num_hidden_layers)]
        for i, blk in enumerate(self.h):
            self.add_sublayer(f"h.{i}", blk)
        self.ln_f = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids):
        from .. import ops
        _, s = input_ids.shape
        pos = ops.arange(0, s, dtype="int32").unsqueeze(0)
        hidden = self.wte(input_ids) + self.wpe(pos)
        hidden = self.drop(hidden)
        for blk in self.h:
            hidden = blk(hidden)
        return self.ln_f(hidden)


class GPT2ForCausalLM(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.config = config
        self.transformer = GPT2Model(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  weight_attr=I.Normal(
                                      std=config.initializer_range),
                                  bias_attr=False)

    def forward(self, input_ids, labels=None):
        hidden = self.transformer(input_ids)
        if self.lm_head is None:
            from .. import ops
            logits = ops.matmul(hidden, self.transformer.wte.weight,
                                transpose_y=True)
        else:
            logits = self.lm_head(hidden)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]).astype("float32"),
            labels.reshape([-1]))
        return logits, loss

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in self.parameters())
