"""paddle.audio.backends — wave io (ref audio/backends over soundfile;
the baked image has no soundfile, so the stdlib wave module covers the
WAV path and other formats raise with a clear message)."""
from __future__ import annotations

import wave as _wave

import numpy as np


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def list_available_backends():
    return ["wave"]


def get_current_backend():
    return "wave"


def set_backend(backend_name):
    if backend_name != "wave":
        raise ValueError("only the builtin 'wave' backend exists offline")


def info(filepath):
    with _wave.open(filepath, "rb") as w:
        return AudioInfo(w.getframerate(), w.getnframes(), w.getnchannels(),
                         w.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    with _wave.open(filepath, "rb") as w:
        sr = w.getframerate()
        n = w.getnframes()
        ch = w.getnchannels()
        width = w.getsampwidth()
        w.setpos(frame_offset)
        count = n - frame_offset if num_frames < 0 else num_frames
        raw = w.readframes(count)
    dtype = {1: np.int8, 2: np.int16, 4: np.int32}[width]
    arr = np.frombuffer(raw, dtype=dtype).reshape(-1, ch)
    if normalize:
        arr = arr.astype(np.float32) / float(2 ** (8 * width - 1))
    data = arr.T if channels_first else arr
    return Tensor(jnp.asarray(data)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    from ..core.tensor import Tensor
    arr = np.asarray(src._data if isinstance(src, Tensor) else src)
    if channels_first:
        arr = arr.T
    if arr.dtype in (np.float32, np.float64):
        arr = (np.clip(arr, -1, 1)
               * (2 ** (bits_per_sample - 1) - 1)).astype(
            {8: np.int8, 16: np.int16, 32: np.int32}[bits_per_sample])
    with _wave.open(filepath, "wb") as w:
        w.setnchannels(arr.shape[1] if arr.ndim > 1 else 1)
        w.setsampwidth(bits_per_sample // 8)
        w.setframerate(sample_rate)
        w.writeframes(arr.tobytes())


__all__ = ["info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend", "AudioInfo"]
