"""paddle.audio.datasets (ref audio/datasets: TESS, ESC50) — offline gated
like the text datasets (archives must be pre-placed)."""
from __future__ import annotations


class _Gated:
    _name = "dataset"

    def __init__(self, *a, **k):
        raise RuntimeError(
            f"paddle.audio.datasets.{self._name} needs its archive "
            f"downloaded; no egress in this environment — build an "
            f"io.Dataset over local files instead")


class TESS(_Gated):
    _name = "TESS"


class ESC50(_Gated):
    _name = "ESC50"


__all__ = ["TESS", "ESC50"]
