"""Audio feature layers.

Reference: python/paddle/audio/features/layers.py — Spectrogram,
MelSpectrogram, LogMelSpectrogram, MFCC as nn Layers. Composed from
signal.stft + the functional filterbank; everything after the window is
one fused XLA computation.
"""
from __future__ import annotations

from ..nn.layer import Layer
from ..ops.linalg import matmul
from . import functional as F


class Spectrogram(Layer):
    """features Spectrogram analog: |STFT|^power, [B, freq, frames]."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer(
            "window", F.get_window(window, self.win_length, dtype=dtype))

    def forward(self, x):
        from ..signal import stft
        spec = stft(x, self.n_fft, self.hop_length, self.win_length,
                    window=self.window, center=self.center,
                    pad_mode=self.pad_mode)
        mag = spec.abs()
        return mag ** self.power if self.power != 1.0 else mag


class MelSpectrogram(Layer):
    """features MelSpectrogram analog."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.n_mels = n_mels
        self.register_buffer("fbank_matrix", F.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype))

    def forward(self, x):
        spec = self._spectrogram(x)                     # [B, freq, frames]
        return matmul(self.fbank_matrix, spec)          # [B, mel, frames]


class LogMelSpectrogram(Layer):
    """features LogMelSpectrogram analog."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return F.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    """features MFCC analog: DCT-II over the log-mel spectrogram."""

    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.register_buffer("dct_matrix",
                             F.create_dct(n_mfcc, n_mels, dtype=dtype))
        self.n_mfcc = n_mfcc

    def forward(self, x):
        logmel = self._log_melspectrogram(x)            # [B, mel, frames]
        # DCT along the mel axis: [n_mels, n_mfcc]^T @ mel
        return matmul(self.dct_matrix.transpose([1, 0]), logmel)


__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]
