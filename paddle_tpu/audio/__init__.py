"""paddle.audio analog: spectral features over the fft/signal stack."""
from __future__ import annotations

from . import features
from . import functional

__all__ = ["features", "functional"]

# -- paddle.audio io surface (ref audio/__init__.py backends + datasets) -----
from . import backends  # noqa: E402
from . import datasets  # noqa: E402
from .backends import info, load, save  # noqa: E402

__all__ += ["backends", "datasets", "info", "load", "save"]
