"""paddle.audio analog: spectral features over the fft/signal stack."""
from __future__ import annotations

from . import features
from . import functional

__all__ = ["features", "functional"]
