"""Audio functional ops.

Reference: python/paddle/audio/functional (hz_to_mel/mel_to_hz/
mel_frequencies/fft_frequencies/compute_fbank_matrix/power_to_db/
create_dct, window functions). Pure array math over jnp via the op
registry — the mel filterbank matmul rides the MXU.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.registry import dispatch


def hz_to_mel(freq, htk=False):
    """functional/functional.py hz_to_mel analog (slaney default)."""
    scalar = isinstance(freq, (int, float))
    f = np.asarray(freq._data if isinstance(freq, Tensor) else freq,
                   dtype=np.float64)
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep, mel)
    return float(mel) if scalar else (Tensor(mel.astype(np.float32))
                                      if isinstance(freq, Tensor) else mel)


def mel_to_hz(mel, htk=False):
    scalar = isinstance(mel, (int, float))
    m = np.asarray(mel._data if isinstance(mel, Tensor) else mel,
                   dtype=np.float64)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        hz = np.where(m >= min_log_mel,
                      min_log_hz * np.exp(logstep * (m - min_log_mel)), hz)
    return float(hz) if scalar else (Tensor(hz.astype(np.float32))
                                     if isinstance(mel, Tensor) else hz)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    lo = hz_to_mel(float(f_min), htk)
    hi = hz_to_mel(float(f_max), htk)
    mels = np.linspace(lo, hi, n_mels)
    return Tensor(mel_to_hz(mels, htk).astype(dtype))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(np.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2]."""
    f_max = f_max or sr / 2.0
    fftfreqs = np.asarray(fft_frequencies(sr, n_fft)._data, dtype=np.float64)
    melfreqs = np.asarray(
        mel_frequencies(n_mels + 2, f_min, f_max, htk)._data,
        dtype=np.float64)
    fdiff = np.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2:n_mels + 2] - melfreqs[:n_mels])
        weights *= enorm[:, None]
    return Tensor(weights.astype(dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """10*log10 with clamping (functional power_to_db analog)."""
    def _impl(x):
        log_spec = 10.0 * jnp.log10(jnp.maximum(x, amin))
        log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec
    x = spect if isinstance(spect, Tensor) else Tensor(np.asarray(spect))
    return dispatch(_impl, (x,), {}, op_name="power_to_db")


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II matrix [n_mels, n_mfcc]."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :]) * 2.0
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(1.0 / (2.0 * n_mels))
    return Tensor(dct.astype(dtype))


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """Window functions (functional/window.py analog)."""
    n = win_length
    if isinstance(window, (tuple, list)):
        window, _ = window[0], window[1:]
    denom = n if fftbins else n - 1
    t = np.arange(n, dtype=np.float64)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * math.pi * t / denom)
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * math.pi * t / denom)
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * math.pi * t / denom)
             + 0.08 * np.cos(4 * math.pi * t / denom))
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(n)
    else:
        raise ValueError(f"unsupported window: {window}")
    return Tensor(w.astype(dtype))


__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct", "get_window"]
