"""Recorder hook seam for the SOT segment compiler (jit/sot.py).

Lives in core so tensor.py / ops/registry.py can notify without importing
the jit package (no import cycle, one list-indexing check when idle —
the same cost profile as the capture/profiler hooks).
"""
from __future__ import annotations

from typing import List, Optional

RECORDER: List[Optional[object]] = [None]


def notify_op(call, in_tensors, out_tensors):
    rec = RECORDER[0]
    if rec is not None:
        rec.on_op(call, in_tensors, out_tensors)


def notify_break(tensor, kind, value):
    rec = RECORDER[0]
    if rec is not None:
        rec.on_break(tensor, kind, value)


def notify_mutation(tensor, new_data):
    rec = RECORDER[0]
    if rec is not None:
        rec.on_mutation(tensor, new_data)
