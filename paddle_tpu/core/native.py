"""Loader + Python facade for the native runtime tier (csrc/native.cc).

The C++ extension provides the infra-critical host-side components the
reference implements natively (SURVEY.md §2.1 dispositions):

- ``TCPStore``      — phi/core/distributed/store/tcp_store.h:121 analog
- ``BlockingQueue`` — fluid/imperative/data_loader.cc blocking-queue analog
- host tracer       — platform/profiler/host_tracer.cc analog
- stat registry     — fluid/memory/stats.h analog

The extension is compiled on first use with g++ straight from csrc/ (the
image has no pybind11; the module uses the raw CPython C API). If the
toolchain is unavailable the pure-Python fallback below provides identical
semantics so the framework never hard-fails.
"""
from __future__ import annotations

import os
import subprocess
import sys
import sysconfig
import threading
import time
from typing import List, Optional

_native = None
_native_err: Optional[str] = None
_load_lock = threading.Lock()


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_and_load():
    """Compile csrc/native.cc into paddle_tpu/_native*.so if needed, import it."""
    global _native, _native_err
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(_repo_root(), "csrc", "native.cc")
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    so_path = os.path.join(pkg_dir, "_native" + suffix)
    try:
        need_build = (not os.path.exists(so_path)
                      or (os.path.exists(src)
                          and os.path.getmtime(src) > os.path.getmtime(so_path)))
        if need_build:
            if not os.path.exists(src):
                raise FileNotFoundError(src)
            include = sysconfig.get_paths()["include"]
            lock_path = so_path + ".lock"
            # crude cross-process build lock (parallel pytest workers)
            for _ in range(600):
                try:
                    fd = os.open(lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    os.close(fd)
                    break
                except FileExistsError:
                    time.sleep(0.1)
            else:
                raise TimeoutError("native build lock timeout")
            try:
                if (not os.path.exists(so_path)
                        or os.path.getmtime(src) > os.path.getmtime(so_path)):
                    tmp = so_path + ".tmp.so"
                    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                           "-I", include, src, "-o", tmp, "-lpthread"]
                    subprocess.run(cmd, check=True, capture_output=True,
                                   timeout=300)
                    os.replace(tmp, so_path)
            finally:
                try:
                    os.remove(lock_path)
                except OSError:
                    pass
        import importlib.util
        spec = importlib.util.spec_from_file_location("paddle_tpu._native",
                                                      so_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _native = mod
    except Exception as e:  # pragma: no cover - toolchain-dependent
        _native_err = f"{type(e).__name__}: {e}"
        _native = None


def get_native():
    """The compiled extension module, or None if unavailable."""
    global _native
    if _native is None and _native_err is None:
        with _load_lock:
            if _native is None and _native_err is None:
                _build_and_load()
    return _native


def native_available() -> bool:
    return get_native() is not None


def native_error() -> Optional[str]:
    get_native()
    return _native_err


# ---------------------------------------------------------------------------
# TCPStore
# ---------------------------------------------------------------------------

class TCPStore:
    """Rank-0-hosted TCP key/value store for multi-host bootstrap.

    Mirrors the reference's TCPStore semantics
    (phi/core/distributed/store/tcp_store.h:121): ``set``/blocking ``get``/
    atomic ``add``/``wait``/``delete_key``, plus a prefix ``list_keys``.
    The master rank starts the in-process server; every rank (including the
    master) talks to it through a client socket.
    """

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 300.0):
        self.host = host
        self.port = port
        self.is_master = is_master
        self.world_size = world_size
        self.timeout = timeout
        self._n = get_native()
        self._server = None
        self._py = None
        if self._n is not None:
            if is_master:
                self._server = self._n.store_server_start("", port)
            self._client = self._n.store_connect(host, port,
                                                 int(timeout * 1000))
        else:  # pure-python fallback
            self._py = _PyStoreBackend(host, port, is_master, timeout)

    def set(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()
        if self._py is not None:
            return self._py.set(key, value)
        self._n.store_set(self._client, key, value)

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        t = self.timeout if timeout is None else timeout
        if self._py is not None:
            return self._py.get(key, t)
        return self._n.store_get(self._client, key, int(t * 1000))

    def add(self, key: str, amount: int = 1) -> int:
        if self._py is not None:
            return self._py.add(key, amount)
        return self._n.store_add(self._client, key, amount)

    def check(self, key: str) -> bool:
        if self._py is not None:
            return self._py.check(key)
        return self._n.store_check(self._client, key)

    def wait(self, keys: List[str], timeout: Optional[float] = None) -> None:
        deadline = time.monotonic() + (timeout or self.timeout)
        for k in keys:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"wait timed out on {k}")
            self.get(k, timeout=remaining)

    def delete_key(self, key: str) -> None:
        if self._py is not None:
            return self._py.delete_key(key)
        self._n.store_delete(self._client, key)

    def list_keys(self, prefix: str = "") -> List[str]:
        if self._py is not None:
            return self._py.list_keys(prefix)
        return self._n.store_list(self._client, prefix)

    def barrier(self, name: str, world_size: Optional[int] = None,
                timeout: Optional[float] = None) -> None:
        """Store-based barrier: everyone adds, then waits for the count."""
        n = world_size or self.world_size
        arrived = self.add(f"__barrier__/{name}/count", 1)
        if arrived == n:
            self.set(f"__barrier__/{name}/done", b"1")
        self.get(f"__barrier__/{name}/done", timeout=timeout)

    def close(self) -> None:
        if self._py is not None:
            self._py.close()
            return
        if self._n is not None:
            try:
                self._n.store_close(self._client)
            except Exception:
                pass
            if self._server is not None:
                self._n.store_server_stop(self._server)
                self._server = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class _PyStoreBackend:
    """socket-based fallback with the same wire protocol semantics (but its
    own implementation — only used when the C++ extension cannot build)."""

    def __init__(self, host, port, is_master, timeout):
        import socket
        import socketserver
        self._kv = {}
        self._cv = threading.Condition()
        self._server = None
        store = self

        if is_master:
            class Handler(socketserver.StreamRequestHandler):
                def handle(self):
                    import pickle
                    while True:
                        try:
                            req = pickle.load(self.rfile)
                        except EOFError:
                            return
                        op = req[0]
                        if op == "set":
                            with store._cv:
                                store._kv[req[1]] = req[2]
                                store._cv.notify_all()
                            resp = None
                        elif op == "get":
                            deadline = time.monotonic() + req[2]
                            with store._cv:
                                while req[1] not in store._kv:
                                    rem = deadline - time.monotonic()
                                    if rem <= 0 or not store._cv.wait(rem):
                                        break
                                resp = store._kv.get(req[1], _TIMEOUT_SENTINEL)
                        elif op == "add":
                            with store._cv:
                                cur = int(store._kv.get(req[1], b"0")) + req[2]
                                store._kv[req[1]] = str(cur).encode()
                                store._cv.notify_all()
                            resp = cur
                        elif op == "check":
                            with store._cv:
                                resp = req[1] in store._kv
                        elif op == "del":
                            with store._cv:
                                store._kv.pop(req[1], None)
                            resp = None
                        elif op == "list":
                            with store._cv:
                                resp = [k for k in store._kv
                                        if k.startswith(req[1])]
                        else:
                            resp = None
                        pickle.dump(resp, self.wfile)
                        self.wfile.flush()

            class Server(socketserver.ThreadingTCPServer):
                allow_reuse_address = True
                daemon_threads = True

            self._server = Server(("", port), Handler)
            threading.Thread(target=self._server.serve_forever,
                             daemon=True).start()

        deadline = time.monotonic() + timeout
        while True:
            try:
                self._sock = socket.create_connection((host, port), timeout=5)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise TimeoutError(f"connect timeout {host}:{port}")
                time.sleep(0.05)
        self._sock_lock = threading.Lock()
        self._rfile = self._sock.makefile("rb")
        self._wfile = self._sock.makefile("wb")

    def _rpc(self, *req):
        import pickle
        with self._sock_lock:
            pickle.dump(req, self._wfile)
            self._wfile.flush()
            return pickle.load(self._rfile)

    def set(self, key, value):
        self._rpc("set", key, value)

    def get(self, key, timeout):
        r = self._rpc("get", key, timeout)
        if r is _TIMEOUT_SENTINEL or (isinstance(r, str)
                                      and r == "__timeout__"):
            raise TimeoutError(key)
        return r

    def add(self, key, amount):
        return self._rpc("add", key, amount)

    def check(self, key):
        return self._rpc("check", key)

    def delete_key(self, key):
        self._rpc("del", key)

    def list_keys(self, prefix):
        return self._rpc("list", prefix)

    def close(self):
        try:
            self._sock.close()
        except Exception:
            pass
        if self._server is not None:
            self._server.shutdown()


_TIMEOUT_SENTINEL = "__timeout__"


# ---------------------------------------------------------------------------
# BlockingQueue
# ---------------------------------------------------------------------------

class BlockingQueue:
    """Bounded blocking queue over the native condvar queue; the prefetch
    buffer of the DataLoader (fluid/imperative/data_loader.cc analog).

    ``pop`` raises StopIteration once closed and drained — matching the
    reference blocking queue's end-of-epoch signal.
    """

    def __init__(self, capacity: int = 8):
        self._n = get_native()
        if self._n is not None:
            self._h = self._n.queue_create(capacity)
            self._q = None
        else:
            import queue
            self._q = queue.Queue(maxsize=capacity)
            self._closed = threading.Event()
            self._h = None

    def push(self, item, timeout: float = -1.0) -> bool:
        if self._h is not None:
            return self._n.queue_push(self._h, item,
                                      int(timeout * 1000) if timeout >= 0 else -1)
        import queue as _q
        if self._closed.is_set():
            raise BrokenPipeError("queue closed")
        try:
            self._q.put(item, timeout=None if timeout < 0 else timeout)
            return True
        except _q.Full:
            return False

    def pop(self, timeout: float = -1.0):
        if self._h is not None:
            return self._n.queue_pop(self._h,
                                     int(timeout * 1000) if timeout >= 0 else -1)
        import queue as _q
        deadline = None if timeout < 0 else time.monotonic() + timeout
        while True:
            try:
                return self._q.get(timeout=0.05)
            except _q.Empty:
                if self._closed.is_set() and self._q.empty():
                    raise StopIteration("queue closed")
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("queue pop timeout")

    def close(self):
        if self._h is not None:
            self._n.queue_close(self._h)
        else:
            self._closed.set()

    def size(self) -> int:
        if self._h is not None:
            return self._n.queue_size(self._h)
        return self._q.qsize()

    def release(self):
        if self._h is not None:
            self._n.queue_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.release()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# tracer + stats facade (used by paddle_tpu.profiler / memory stats)
# ---------------------------------------------------------------------------

class _PyTracer:
    def __init__(self):
        self.enabled = False
        self._events = []
        self._open = {}
        self._next = 1
        self._lock = threading.Lock()

    def begin(self, name):
        if not self.enabled:
            return 0
        with self._lock:
            i = self._next
            self._next += 1
            self._open[i] = (name, threading.get_ident(),
                             time.monotonic_ns())
        return i

    def end(self, i):
        if i == 0:
            return
        with self._lock:
            ev = self._open.pop(i, None)
            if ev is not None:
                self._events.append((ev[0], ev[1], ev[2],
                                     time.monotonic_ns()))

    def instant(self, name):
        if not self.enabled:
            return
        t = time.monotonic_ns()
        with self._lock:
            self._events.append((name, threading.get_ident(), t, t))

    def drain(self):
        with self._lock:
            evs, self._events = self._events, []
        return evs

    def clear(self):
        with self._lock:
            self._events = []
            self._open = {}


_py_tracer = _PyTracer()
_py_stats = {}
_py_stats_lock = threading.Lock()


def tracer_enable(flag: bool) -> None:
    n = get_native()
    if n is not None:
        n.tracer_enable(bool(flag))
    else:
        _py_tracer.enabled = bool(flag)


def tracer_enabled() -> bool:
    n = get_native()
    return n.tracer_enabled() if n is not None else _py_tracer.enabled


def tracer_begin(name: str) -> int:
    n = get_native()
    return n.tracer_begin(name) if n is not None else _py_tracer.begin(name)


def tracer_end(ident: int) -> None:
    n = get_native()
    if n is not None:
        n.tracer_end(ident)
    else:
        _py_tracer.end(ident)


def tracer_instant(name: str) -> None:
    n = get_native()
    if n is not None:
        n.tracer_instant(name)
    else:
        _py_tracer.instant(name)


def tracer_drain():
    """-> list of (name, tid, start_ns, end_ns)."""
    n = get_native()
    return n.tracer_drain() if n is not None else _py_tracer.drain()


def tracer_clear() -> None:
    n = get_native()
    if n is not None:
        n.tracer_clear()
    else:
        _py_tracer.clear()


def stat_update(name: str, delta: int) -> int:
    """DEVICE_MEMORY_STAT-style named counter update; returns current."""
    n = get_native()
    if n is not None:
        return n.stat_update(name, int(delta))
    with _py_stats_lock:
        cur, peak = _py_stats.get(name, (0, 0))
        cur += int(delta)
        _py_stats[name] = (cur, max(peak, cur))
        return cur


def stat_get(name: str):
    """-> (current, peak)."""
    n = get_native()
    if n is not None:
        return n.stat_get(name)
    with _py_stats_lock:
        return _py_stats.get(name, (0, 0))


def stat_reset(name: str) -> None:
    n = get_native()
    if n is not None:
        n.stat_reset(name)
    else:
        with _py_stats_lock:
            _py_stats.pop(name, None)


def stat_all():
    n = get_native()
    if n is not None:
        return n.stat_all()
    with _py_stats_lock:
        return dict(_py_stats)
