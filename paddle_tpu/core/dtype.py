"""Dtype utilities.

TPU-native analog of the reference's dtype system (paddle/phi/common/data_type.h,
python/paddle/framework/dtype.py). We canonicalize everything onto jnp dtypes and
keep paddle-style string names ('float32', 'bfloat16', ...).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype aliases (paddle exposes these as paddle.float32 etc.)
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
uint64 = jnp.uint64
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR_TO_DTYPE = {
    "float16": float16,
    "fp16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "fp64": float64,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "uint8": uint8,
    "uint16": uint16,
    "uint32": uint32,
    "uint64": uint64,
    "bool": bool_,
    "complex64": complex64,
    "complex128": complex128,
}

_DEFAULT_DTYPE = [float32]


def set_default_dtype(dtype):
    """paddle.set_default_dtype analog (python/paddle/framework/framework.py)."""
    _DEFAULT_DTYPE[0] = to_jax_dtype(dtype)


def get_default_dtype():
    return _DEFAULT_DTYPE[0]


def to_jax_dtype(dtype):
    """Canonicalize a dtype spec (str / np dtype / jnp dtype / None) to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower()
        if key not in _STR_TO_DTYPE:
            raise ValueError(f"unknown dtype string: {dtype!r}")
        return _STR_TO_DTYPE[key]
    return np.dtype(dtype).type if not hasattr(dtype, "dtype") else dtype


def dtype_name(dtype) -> str:
    """Return the paddle-style string name for a dtype."""
    return np.dtype(dtype).name


def is_floating(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.integer)
