"""Program-capture context for the compiled (to_static) path.

TPU-native replacement for the reference's dynamic-to-static machinery
(paddle/fluid/pybind/eval_frame.c frame hook + jit/sot bytecode simulation,
SURVEY.md §2.13). Instead of simulating CPython bytecode, we run the function
once eagerly under a capture context that records its *implicit state*:

  - every pre-existing Tensor read by an op (params, buffers, constants),
  - every Tensor mutated via _set_data (BatchNorm running stats, setitem),
  - every Tensor receiving a gradient (leaf .grad writes),
  - whether the global RNG was consumed.

The second pass binds all recorded state as jax.jit inputs and returns the
mutated state as outputs — a pure function XLA can compile, equivalent to the
reference's partial_program forward+backward wrapped in a run_program op.
"""
from __future__ import annotations

from typing import Dict, List, Optional

_ACTIVE: List["CaptureContext"] = []


class CaptureContext:
    def __init__(self):
        self.reads: Dict[int, object] = {}      # id -> Tensor (pre-existing)
        self.mutated: Dict[int, object] = {}    # id -> Tensor (data replaced)
        self.grad_writes: Dict[int, object] = {}  # id -> Tensor (.grad written)
        self.created: set = set()               # ids of tensors born in-trace
        self.rng_used = False

    # -- hooks --------------------------------------------------------------
    def record_read(self, t):
        if id(t) not in self.created and id(t) not in self.reads:
            self.reads[id(t)] = t

    def record_created(self, t):
        self.created.add(id(t))

    def record_mutation(self, t):
        if id(t) not in self.created:
            self.mutated[id(t)] = t
            # a mutated tensor is also state even if never read before
            self.reads.setdefault(id(t), t)

    def record_grad_write(self, t):
        if id(t) not in self.created:
            self.grad_writes[id(t)] = t

    def record_rng(self):
        self.rng_used = True

    def __enter__(self):
        _ACTIVE.append(self)
        return self

    def __exit__(self, *exc):
        _ACTIVE.pop()
        return False


def active() -> Optional[CaptureContext]:
    return _ACTIVE[-1] if _ACTIVE else None
