"""Small framework-level shims completing python/paddle/__init__.py parity.

iinfo/finfo (paddle/fluid/pybind: bind numpy-backed dtype info), dtype,
set_printoptions, LazyGuard (lazy parameter init), place shims, the legacy
`paddle.batch` reader decorator, and rng-state accessors.
"""
from __future__ import annotations

import numpy as np

from . import dtype as dtype_mod
from .tensor import CPUPlace, Place, Tensor, TPUPlace


class iinfo:
    """paddle.iinfo — integer dtype limits (numpy-backed like the ref)."""

    def __init__(self, dtype):
        import jax.numpy as jnp
        info = np.iinfo(jnp.dtype(dtype_mod.to_jax_dtype(dtype)))
        self.min = int(info.min)
        self.max = int(info.max)
        self.bits = int(info.bits)
        self.dtype = str(info.dtype)

    def __repr__(self):
        return (f"paddle.iinfo(min={self.min}, max={self.max}, "
                f"bits={self.bits}, dtype={self.dtype})")


class finfo:
    """paddle.finfo — floating dtype limits."""

    def __init__(self, dtype):
        jd = dtype_mod.to_jax_dtype(dtype)
        import jax.numpy as jnp
        info = jnp.finfo(jd)
        self.min = float(info.min)
        self.max = float(info.max)
        self.eps = float(info.eps)
        self.tiny = float(info.tiny)
        self.smallest_normal = float(info.tiny)
        self.resolution = float(getattr(info, "resolution", info.eps))
        self.bits = int(info.bits)
        self.dtype = str(jnp.dtype(jd))

    def __repr__(self):
        return (f"paddle.finfo(min={self.min}, max={self.max}, "
                f"eps={self.eps}, bits={self.bits}, dtype={self.dtype})")


def dtype(name):
    """paddle.dtype — dtype constructor/alias (paddle.dtype('float32'))."""
    return dtype_mod.to_jax_dtype(name)


_PRINT_OPTS = {"precision": 8, "threshold": 1000, "edgeitems": 3,
               "linewidth": 80, "sci_mode": None}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """paddle.set_printoptions — forwards to numpy's print options (tensor
    repr renders through numpy here)."""
    kw = {}
    if precision is not None:
        _PRINT_OPTS["precision"] = precision
        kw["precision"] = precision
    if threshold is not None:
        _PRINT_OPTS["threshold"] = threshold
        kw["threshold"] = threshold
    if edgeitems is not None:
        _PRINT_OPTS["edgeitems"] = edgeitems
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        _PRINT_OPTS["linewidth"] = linewidth
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        _PRINT_OPTS["sci_mode"] = sci_mode
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


class LazyGuard:
    """paddle.LazyGuard analog (python/paddle/base/framework.py LazyGuard):
    in the reference, layers built inside the guard defer parameter
    initialization until explicitly materialized. Initialization here is
    cheap host-side numpy (no device traffic until first use), so the guard
    is a compatible no-op context."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class CUDAPlace(Place):
    """Compatibility shim: accepted wherever a place is, maps to the TPU
    device (there is no CUDA in this build)."""

    def __init__(self, device_id=0):
        super().__init__("tpu", device_id)


class CUDAPinnedPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class XPUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__("tpu", device_id)


def batch(reader, batch_size, drop_last=False):
    """Legacy paddle.batch reader decorator (python/paddle/reader):
    groups a sample reader into batches."""

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def check_shape(shape):
    """Validate a shape argument (paddle static helper)."""
    if isinstance(shape, Tensor):
        return
    for d in shape:
        if isinstance(d, int) and d < -1:
            raise ValueError(f"invalid dim {d} in shape {shape}")


def get_rng_state(device=None):
    from . import random as _random
    return [_random.default_generator().get_state()]


def set_rng_state(state_list, device=None):
    from . import random as _random
    state = state_list[0] if isinstance(state_list, (list, tuple)) \
        else state_list
    _random.default_generator().set_state(state)


def get_cuda_rng_state():
    return get_rng_state()


def set_cuda_rng_state(state_list):
    return set_rng_state(state_list)


def disable_signal_handler():
    """No-op: the reference installs C++ signal handlers; this runtime
    leaves python's handlers untouched."""


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """paddle.create_parameter (static helper): a free-standing Parameter."""
    import jax.numpy as jnp

    from .tensor import Parameter
    jd = dtype_mod.to_jax_dtype(dtype)
    if default_initializer is not None:
        from ..nn.layer import Layer
        holder = Layer()
        p = holder.create_parameter(list(shape), attr=attr, dtype=dtype,
                                    is_bias=is_bias,
                                    default_initializer=default_initializer)
        return p
    if is_bias:
        data = jnp.zeros(tuple(shape), jd)
    else:
        import numpy as _np
        fan_in = shape[0] if shape else 1
        limit = float(_np.sqrt(6.0 / max(fan_in, 1)))
        from ..nn.functional import random_mod
        import jax
        data = jax.random.uniform(random_mod.next_key(), tuple(shape), jd,
                                  -limit, limit)
    p = Parameter(data)
    p.name = name
    return p


__all__ = ["iinfo", "finfo", "dtype", "set_printoptions", "LazyGuard",
           "CUDAPlace", "CUDAPinnedPlace", "XPUPlace", "batch",
           "check_shape", "get_rng_state", "set_rng_state",
           "get_cuda_rng_state", "set_cuda_rng_state",
           "disable_signal_handler", "create_parameter"]
