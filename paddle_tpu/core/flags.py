"""Runtime flag registry.

Analog of the reference's unified flag system (paddle/utils/flags.h:42,
paddle/phi/core/flags.cc — ~96 exported FLAGS_* runtime flags surfaced through
paddle.set_flags / get_flags). Flags may also be seeded from FLAGS_* environment
variables at import time, matching the reference's env override behavior.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Iterable, Union

_LOCK = threading.Lock()
_FLAGS: Dict[str, Any] = {}
_DEFS: Dict[str, dict] = {}


def define_flag(name: str, default: Any, help_str: str = "") -> None:
    """Register a flag (PHI_DEFINE_EXPORTED_* analog)."""
    with _LOCK:
        _DEFS[name] = {"default": default, "help": help_str, "type": type(default)}
        env = os.environ.get(name)
        if env is not None:
            _FLAGS[name] = _parse(env, type(default))
        else:
            _FLAGS.setdefault(name, default)


def _parse(value: str, ty: type) -> Any:
    if ty is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if ty in (int, float):
        return ty(value)
    return value


_FLAG_OBSERVERS: Dict[str, Any] = {}  # flag name -> callback(value)


def observe_flag(name: str, callback) -> None:
    """Register a callback fired when set_flags changes `name` (used by
    amp.debugging so FLAGS_check_nan_inf activates the dispatch hook)."""
    _FLAG_OBSERVERS[name] = callback


def set_flags(flags: Dict[str, Any]) -> None:
    """paddle.set_flags analog (python/paddle/base/framework.py)."""
    notify = []
    with _LOCK:
        for k, v in flags.items():
            if k not in _DEFS:
                raise KeyError(f"unknown flag {k!r}")
            _FLAGS[k] = v
            if k in _FLAG_OBSERVERS:
                notify.append((k, v))
    for k, v in notify:  # outside the lock: callbacks may read flags
        _FLAG_OBSERVERS[k](v)


def get_flags(flags: Union[str, Iterable[str]]) -> Dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    with _LOCK:
        return {k: _FLAGS[k] for k in flags}


def get_flag(name: str) -> Any:
    with _LOCK:
        return _FLAGS[name]


# Core flags mirroring the reference's most load-bearing ones.
define_flag("FLAGS_check_nan_inf", False, "scan op outputs for NaN/Inf (phi/core/flags.cc:74)")
define_flag("FLAGS_cudnn_deterministic", False, "deterministic kernels")
define_flag("FLAGS_low_precision_op_list", 0, "record low precision op calls")
define_flag("FLAGS_allocator_strategy", "auto_growth", "host allocator strategy")
define_flag("FLAGS_eager_op_cache", True, "cache per-op jitted executables in eager mode")
define_flag("FLAGS_use_pallas_attention", True,
            "route attention to the Pallas flash kernel on TPU when shapes "
            "allow (reference: dynloaded flashattn, N27)")
define_flag("FLAGS_flash_autotune", False,
            "measure flash-attention (block_q, block_k) tilings on-device "
            "at the first call per (shape, dtype) and cache the winner; "
            "traced calls tune on synthesized arrays, so compiled training "
            "benefits too (TPU only; reference analog: per-arch tuned "
            "flashattn binaries; multi-controller: tune rank 0, broadcast "
            "via autotune.set_best)")
define_flag("FLAGS_use_pallas_rmsnorm", True,
            "route weighted rms_norm to the fused Pallas kernel on TPU "
            "(reference: fused_rms_norm in phi/kernels/fusion)")
define_flag("FLAGS_use_pallas_adamw", False,
            "route the AdamW update to the single-pass Pallas kernel on TPU "
            "(reference: fused_adam, phi/kernels/fusion/gpu); default off — "
            "XLA's fused elementwise chain is equivalent for most shapes")
define_flag("FLAGS_dataloader_mp_context", "fork",
            "multiprocessing start method for DataLoader workers ('fork' is "
            "fast but workers must not touch jax; 'spawn' is always safe)")
