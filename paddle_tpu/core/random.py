"""Global RNG state.

Analog of the reference's Generator (paddle/phi/core/generator.h) and paddle.seed
(python/paddle/framework/random.py). We keep a splittable JAX PRNG key as the
global generator; every consumer splits a fresh subkey. Distributed RNG trackers
(TP rank-distinct seeds, fleet/layers/mpu/random.py:34 RNGStatesTracker) build on
fork_rng_state below.
"""
from __future__ import annotations

import jax


class Generator:
    """Lazy: the PRNG key materializes on first use so that merely importing
    the framework never initializes the jax backend (device discovery at
    import time breaks launcher/tooling processes that only need the API)."""

    def __init__(self, seed: int = 0):
        self._seed = seed
        self._key = None

    def _ensure(self):
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)

    def manual_seed(self, seed: int):
        self._seed = seed
        self._key = None  # stays lazy: seeding must not touch the backend
        return self

    def next_key(self):
        from . import capture
        cap = capture.active()
        if cap is not None:
            cap.record_rng()
        self._ensure()
        self._key, sub = jax.random.split(self._key)
        return sub

    def get_state(self):
        self._ensure()
        return self._key

    def set_state(self, key):
        self._key = key

    def initial_seed(self) -> int:
        return self._seed


_GLOBAL_GENERATOR = Generator(0)


def default_generator() -> Generator:
    return _GLOBAL_GENERATOR


def seed(s: int) -> Generator:
    """paddle.seed analog."""
    return _GLOBAL_GENERATOR.manual_seed(s)


def next_key():
    return _GLOBAL_GENERATOR.next_key()


def fork_rng_state(offset: int):
    """Derive a deterministic key stream offset from the current global key
    (used by the TP RNGStatesTracker analog)."""
    return jax.random.fold_in(_GLOBAL_GENERATOR.get_state(), offset)
