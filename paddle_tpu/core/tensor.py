"""Eager Tensor.

TPU-native redesign of the reference's eager Tensor
(paddle/phi/core/dense_tensor.h:37 DenseTensor + paddle/fluid/eager/autograd_meta.h:61
AutogradMeta + pybind eager_method.cc). Here a Tensor wraps a jax.Array (an XLA
buffer on TPU, or a tracer under jit capture) plus autograd metadata; all kernels
are XLA/Pallas, dispatched through the op layer (paddle_tpu.ops).

Paddle semantics preserved: `stop_gradient` defaults to True for data tensors and
False for Parameters; `.backward()` runs the tape engine; `.grad` accumulates on
leaves; `.clear_grad()` zeroes it.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtype_mod
from . import sot_hooks
from ..autograd import engine as _engine


class Place:
    def __init__(self, kind: str, index: int = 0):
        self.kind = kind
        self.index = index

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        return isinstance(other, Place) and (self.kind, self.index) == (other.kind, other.index)

    def __hash__(self):
        return hash((self.kind, self.index))


def TPUPlace(index: int = 0) -> Place:
    return Place("tpu", index)


def CPUPlace() -> Place:
    return Place("cpu", 0)


def _unwrap(value):
    return value._data if isinstance(value, Tensor) else value


class Tensor:
    """Eager tensor over a jax.Array. dense_tensor.h:37 / eager.cc analog."""

    # Populated by paddle_tpu.ops at import time (method installation mirrors
    # the reference's math-op patch, pybind/eager_math_op_patch.cc).
    __slots__ = ("_data", "stop_gradient", "_grad", "_grad_node", "_grad_out_idx",
                 "name", "persistable", "_dist_attr", "__weakref__")

    def __init__(self, data, dtype=None, stop_gradient: bool = True, name: Optional[str] = None):
        dt = dtype_mod.to_jax_dtype(dtype)
        if isinstance(data, Tensor):
            data = data._data
        if isinstance(data, (jax.Array, jax.core.Tracer)):
            self._data = data.astype(dt) if dt is not None and data.dtype != np.dtype(dt) else data
        else:
            arr = np.asarray(data)
            if dt is None and arr.dtype == np.float64:
                dt = dtype_mod.get_default_dtype()
            self._data = jnp.asarray(arr, dtype=dt)
        self.stop_gradient = stop_gradient
        self._grad: Optional[Tensor] = None
        self._grad_node = None
        self._grad_out_idx = 0
        self.name = name
        self.persistable = False
        self._dist_attr = None

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self) -> Place:
        try:
            dev = self._data.devices().pop()
            return Place(dev.platform, dev.id)
        except Exception:
            return Place("traced", 0)

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def T(self):
        from .. import ops
        return ops.transpose(self, list(range(self.ndim))[::-1])

    # -- conversion ---------------------------------------------------------
    # each materialization notifies the SOT recorder: these are the graph
    # breaks of the segment compiler (jit/sot.py)
    def numpy(self) -> np.ndarray:
        a = np.asarray(self._data)
        if sot_hooks.RECORDER[0] is not None:
            sot_hooks.notify_break(self, "numpy", a)
        return a

    def item(self):
        v = self._data.item()
        if sot_hooks.RECORDER[0] is not None:
            sot_hooks.notify_break(self, "item", v)
        return v

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        v = float(self._data.item())
        if sot_hooks.RECORDER[0] is not None:
            sot_hooks.notify_break(self, "float", v)
        return v

    def __int__(self):
        v = int(self._data.item())
        if sot_hooks.RECORDER[0] is not None:
            sot_hooks.notify_break(self, "int", v)
        return v

    def __bool__(self):
        v = bool(self._data)
        if sot_hooks.RECORDER[0] is not None:
            sot_hooks.notify_break(self, "bool", v)
        return v

    # -- autograd -----------------------------------------------------------
    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, value):
        self._grad = value if (value is None or isinstance(value, Tensor)) else Tensor(value)

    def _accumulate_grad(self, g):
        """AccumulationNode analog (eager/accumulation/accumulation_node.h)."""
        from . import capture
        cap = capture.active()
        if cap is not None:
            cap.record_grad_write(self)
        if isinstance(g, Tensor):
            # create_graph mode: keep the grad's tape history
            self._grad = g if self._grad is None else self._grad + g
        elif self._grad is None:
            self._grad = Tensor(g)
        else:
            self._grad = Tensor(self._grad._data + g)

    def backward(self, grad_tensor=None, retain_graph: bool = False):
        """z.backward() → engine RunBackward (eager/backward.cc:429)."""
        _engine.run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        return Tensor(self._data, stop_gradient=True, name=self.name)

    def clone(self) -> "Tensor":
        from .. import ops
        return ops.assign(self)

    def stop_gradient_(self, flag: bool = True):
        self.stop_gradient = flag
        return self

    # -- mutation (in-place surface; functional underneath) -----------------
    def _set_data(self, new_data):
        from . import capture
        cap = capture.active()
        if cap is not None:
            cap.record_mutation(self)
        if sot_hooks.RECORDER[0] is not None:
            sot_hooks.notify_mutation(self, new_data)
        self._data = new_data

    def set_value(self, value):
        value = _unwrap(value)
        # through _set_data so capture and the SOT recorder observe it
        self._set_data(jnp.asarray(value, dtype=self.dtype)
                       .reshape(self._data.shape))
        return self

    def copy_(self, other, blocking: bool = True):
        return self.set_value(other)

    # -- misc ---------------------------------------------------------------
    def astype(self, dtype) -> "Tensor":
        from .. import ops
        return ops.cast(self, dtype)

    cast = astype

    def to(self, *args, **kwargs) -> "Tensor":
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, str) and a.lower() in ("cpu", "tpu", "gpu"):
                continue  # single-process placement is XLA's concern
            dtype = a
        return self.astype(dtype) if dtype is not None else self

    def cpu(self):
        return Tensor(np.asarray(self._data), stop_gradient=self.stop_gradient)

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    def __len__(self):
        if not self._data.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        grad_flag = f", stop_gradient={self.stop_gradient}"
        try:
            data_repr = repr(np.asarray(self._data))
        except Exception:
            data_repr = repr(self._data)
        return (f"Tensor(shape={self.shape}, dtype={dtype_mod.dtype_name(self.dtype)}"
                f"{grad_flag},\n       {data_repr})")

    __hash__ = object.__hash__

    # -- indexing (ops installs autograd-aware __getitem__/__setitem__) -----

    def register_hook(self, hook):
        """Per-tensor grad hook (eager grad hooks analog). Wraps the grad node edge."""
        from ..autograd.hooks import register_tensor_hook
        return register_tensor_hook(self, hook)


class Parameter(Tensor):
    """Trainable parameter (python/paddle/base/framework.py Parameter analog)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed")

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.persistable = True

    @property
    def trainable_(self):
        return self.trainable

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()
