from . import dtype, flags, random
from .tensor import CPUPlace, Parameter, Place, Tensor, TPUPlace
