"""Profiler statistics.

Reference: python/paddle/profiler/profiler_statistic.py — aggregates the
event tree into per-name tables (calls, total/avg/max/min, ratio).
"""
from __future__ import annotations

from typing import Dict, List


_UNIT_DIV = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}


class _Row:
    __slots__ = ("name", "calls", "total_ns", "max_ns", "min_ns")

    def __init__(self, name):
        self.name = name
        self.calls = 0
        self.total_ns = 0
        self.max_ns = 0
        self.min_ns = None

    def add(self, dur_ns):
        self.calls += 1
        self.total_ns += dur_ns
        self.max_ns = max(self.max_ns, dur_ns)
        self.min_ns = dur_ns if self.min_ns is None else min(self.min_ns,
                                                             dur_ns)


class SummaryView:
    def __init__(self, rows: List[_Row], wall_ns: int, time_unit: str):
        self.rows = rows
        self.wall_ns = max(wall_ns, 1)
        self.time_unit = time_unit

    def render(self) -> str:
        div = _UNIT_DIV[self.time_unit]
        header = (f"{'Name':<40} {'Calls':>7} {'Total(' + self.time_unit + ')':>12} "
                  f"{'Avg':>10} {'Max':>10} {'Min':>10} {'Ratio(%)':>9}")
        lines = [header, "-" * len(header)]
        for r in sorted(self.rows, key=lambda r: -r.total_ns):
            lines.append(
                f"{r.name[:40]:<40} {r.calls:>7} {r.total_ns / div:>12.4f} "
                f"{r.total_ns / r.calls / div:>10.4f} {r.max_ns / div:>10.4f} "
                f"{(r.min_ns or 0) / div:>10.4f} "
                f"{100.0 * r.total_ns / self.wall_ns:>9.2f}")
        return "\n".join(lines)

    def row(self, name):
        for r in self.rows:
            if r.name == name:
                return r
        return None


def build_summary(events, time_unit="ms") -> SummaryView:
    rows: Dict[str, _Row] = {}
    lo, hi = None, 0
    for ev in events:
        rows.setdefault(ev.name, _Row(ev.name)).add(ev.end_ns - ev.start_ns)
        lo = ev.start_ns if lo is None else min(lo, ev.start_ns)
        hi = max(hi, ev.end_ns)
    wall = (hi - lo) if lo is not None else 0
    return SummaryView(list(rows.values()), wall, time_unit)
