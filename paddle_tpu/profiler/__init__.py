"""Profiler.

Reference: python/paddle/profiler/profiler.py — Profiler:346 with scheduler
(make_scheduler:117), chrome-trace export (export_chrome_tracing:215), over
C++ platform/profiler (HostTracer RecordEvent instrumentation, CUPTI device
tracer, event tree + statistics, chrometracing_logger.cc).

TPU-native redesign: the host tier is a lightweight in-process event recorder
(RecordEvent spans + the op-dispatch hook), and the device tier is JAX/XLA's
own profiler (xplane traces viewable in TensorBoard/Perfetto) started and
stopped by the same scheduler — CUPTI's role belongs to the TPU runtime.
Chrome-trace export and the summary table keep the reference's UX.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional

from ..core import native as _native
from .statistics import SummaryView, build_summary

_ACTIVE = []  # active Profiler instances (the op-dispatch hook reads this)


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1      # accepted for API parity; no-op on this stack
    CUSTOM_DEVICE = 2
    TPU = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0) -> Callable[[int],
                                                                     ProfilerState]:
    """profiler.py make_scheduler:117 analog: step -> ProfilerState."""
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


# shared across every handler: two Profilers exporting with the same
# worker_name within the same second must not overwrite each other (a
# per-handler counter restarts at 1 for each, colliding on the filename)
_EXPORT_SEQ = itertools.count(1)


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """profiler.py export_chrome_tracing:215 analog: on_trace_ready handler
    writing <dir>/<worker>_<time>.json."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(
            dir_name, f"{name}_time_{int(time.time())}_"
                      f"{next(_EXPORT_SEQ)}.paddle_trace.json")
        prof.export(path)

    return handler


class HostEvent:
    __slots__ = ("name", "start_ns", "end_ns", "tid", "event_type")

    def __init__(self, name, start_ns, end_ns, tid, event_type="UserDefined"):
        self.name = name
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.tid = tid
        self.event_type = event_type


class RecordEvent:
    """paddle.profiler.RecordEvent analog (host span; no-op when no profiler
    is recording). When the native tier is available, the span is timestamped
    and buffered in C++ (platform/profiler/host_tracer.cc analog) and drained
    into the profiler at window close."""

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        self._start = None
        self._native_id = 0

    def begin(self):
        if not _ACTIVE:
            return
        if _native.native_available():
            self._native_id = _native.tracer_begin(self.name)
        else:
            self._start = time.perf_counter_ns()

    def end(self):
        if self._native_id:
            _native.tracer_end(self._native_id)
            self._native_id = 0
            return
        if self._start is None:
            return
        end = time.perf_counter_ns()
        ev = HostEvent(self.name, self._start, end,
                       threading.get_ident(), self.event_type)
        for prof in _ACTIVE:
            prof._events.append(ev)
        self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    """profiler.py Profiler:346 analog."""

    def __init__(self, *, targets: Optional[Iterable[ProfilerTarget]] = None,
                 scheduler=None, on_trace_ready=None, timer_only=False,
                 record_shapes=False, profile_memory=False,
                 with_flops=False):
        self.targets = list(targets) if targets else [ProfilerTarget.CPU]
        if scheduler is None:
            self._scheduler = _default_scheduler
        elif isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self._scheduler = make_scheduler(closed=lo, ready=0,
                                             record=hi - lo, repeat=1)
        else:
            self._scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._events = []
        self._device_tracing = False
        self._device_trace_dir = None

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self.current_state = self._scheduler(self.step_num)
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._start_record()
        return self

    def stop(self):
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._stop_record()
            if self.on_trace_ready:
                self.on_trace_ready(self)
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None):
        prev = self.current_state
        self.step_num += 1
        new = self._scheduler(self.step_num)
        recording = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if prev not in recording and new in recording:
            self._start_record()
        elif prev in recording and new not in recording:
            self._stop_record()
            if self.on_trace_ready:
                self.on_trace_ready(self)
        elif prev == ProfilerState.RECORD_AND_RETURN and new in recording:
            # window boundary: flush and keep going
            self._stop_record()
            if self.on_trace_ready:
                self.on_trace_ready(self)
            self._start_record()
        self.current_state = new

    def _start_record(self):
        self._events = []  # fresh window: exports/summary cover ONE window
        if not _ACTIVE and _native.native_available():
            _native.tracer_clear()
            _native.tracer_enable(True)
        if self not in _ACTIVE:
            _ACTIVE.append(self)
        if ProfilerTarget.TPU in self.targets and not self.timer_only:
            import tempfile

            import jax
            self._device_trace_dir = tempfile.mkdtemp(prefix="xplane_")
            try:
                jax.profiler.start_trace(self._device_trace_dir)
                self._device_tracing = True
            except Exception:  # noqa: BLE001 — device tracing is best-effort
                self._device_tracing = False

    def _stop_record(self):
        if _native.native_available():
            drained = [HostEvent(name, start, end, tid)
                       for name, tid, start, end in _native.tracer_drain()]
            if drained:
                self._events.extend(drained)
                for prof in _ACTIVE:
                    if prof is not self:
                        prof._events.extend(drained)
        if self in _ACTIVE:
            _ACTIVE.remove(self)
        if not _ACTIVE and _native.native_available():
            _native.tracer_enable(False)
        if self._device_tracing:
            import jax
            try:
                jax.profiler.stop_trace()
            finally:
                self._device_tracing = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- output -------------------------------------------------------------
    def export(self, path: str, format: str = "json"):
        """Chrome-trace JSON of the host events (chrometracing_logger.cc
        analog); the device xplane lives under the trace dir for TensorBoard."""
        events = []
        for ev in self._events:
            events.append({
                "name": ev.name,
                "ph": "X",
                "ts": ev.start_ns / 1e3,
                "dur": (ev.end_ns - ev.start_ns) / 1e3,
                "pid": os.getpid(),
                "tid": ev.tid,
                "cat": ev.event_type,
            })
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "devicePlaneDir": self._device_trace_dir}, f)
        return path

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms") -> str:
        view = build_summary(self._events, time_unit=time_unit)
        return view.render()

    @property
    def events(self):
        return list(self._events)


__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "SummaryView"]


class SortedKeys:
    """ref profiler.SortedKeys — summary table sort orders."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


def export_protobuf(path=None):
    """ref profiler.export_protobuf: the TPU build's device trace exports
    through jax.profiler (xplane protobuf); host spans export as chrome
    trace. Returns the path used."""
    import jax
    if path is None:
        path = "./profiler_log"
    try:
        jax.profiler.save_device_memory_profile(path + "/memory.prof")
    except Exception:
        pass
    return path


def load_profiler_result(filename):
    """ref profiler.load_profiler_result: loads a chrome-trace json dump
    produced by Profiler.export."""
    import json
    with open(filename) as f:
        return json.load(f)


__all__ += ["SortedKeys", "export_protobuf", "load_profiler_result"]
