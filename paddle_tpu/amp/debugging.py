"""AMP debugging tools.

Reference: python/paddle/amp/debugging.py — operator stats collection
(enable/disable_operator_stats_collection, collect_operator_stats),
check_numerics / TensorCheckerConfig (FLAGS_check_nan_inf,
eager/nan_inf_utils.cc), and compare_accuracy.

TPU-native: both hooks ride the single op-dispatch path (ops/registry.py)
— stats count (op, dtype) pairs per call; the numerics checker runs a
device-side isfinite reduction on op outputs (synchronizing, so debug
only — the reference's nan_inf scan has the same cost profile).
"""
from __future__ import annotations

import contextlib
from collections import defaultdict
from enum import Enum
from typing import Dict, Optional

import numpy as np

_op_stats: Optional[Dict[str, Dict[str, int]]] = None
_checker = {"enabled": False, "debug_mode": None, "stack": True}


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


class TensorCheckerConfig:
    """debugging.py TensorCheckerConfig analog. When ``output_dir`` is set,
    every checked op's outputs are accumulated and written as one .npz per
    process on disable_tensor_checker() — the input compare_accuracy
    consumes."""

    def __init__(self, enable: bool,
                 debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir: Optional[str] = None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = set(checked_op_list or [])
        self.skipped_op_list = set(skipped_op_list or [])
        self.debug_step = debug_step  # (start, end) step window or None
        self.stack_height_limit = stack_height_limit
        self._dump: Dict[str, np.ndarray] = {}
        self._step = 0

    def _should_check(self, op_name: str) -> bool:
        if self.debug_step is not None:
            lo, hi = self.debug_step
            if not (lo <= self._step < hi):
                return False
        if self.checked_op_list and op_name not in self.checked_op_list:
            return False
        return op_name not in self.skipped_op_list


_active_config: Optional[TensorCheckerConfig] = None


def _sync_hook():
    from ..ops.registry import set_debug_hook
    active = _active_config is not None or _op_stats is not None
    set_debug_hook(_dispatch_post_hook if active else None)


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    """debugging.py enable_tensor_checker analog (also flips
    FLAGS_check_nan_inf so the dispatch hook activates)."""
    global _active_config
    _active_config = checker_config if checker_config.enable else None
    from ..core.flags import set_flags
    set_flags({"FLAGS_check_nan_inf": bool(_active_config)})
    _sync_hook()


def disable_tensor_checker():
    global _active_config
    cfg = _active_config
    _active_config = None
    if cfg is not None and cfg.output_dir and cfg._dump:
        import os
        os.makedirs(cfg.output_dir, exist_ok=True)
        np.savez(os.path.join(cfg.output_dir,
                              f"worker_{os.getpid()}.npz"), **cfg._dump)
        cfg._dump = {}
    from ..core.flags import set_flags
    set_flags({"FLAGS_check_nan_inf": False})
    _sync_hook()


def _on_nan_inf_flag(value):
    """core.flags observer: paddle.set_flags({'FLAGS_check_nan_inf': True})
    activates a default checker (reference flag behavior)."""
    global _active_config
    if value and _active_config is None:
        _active_config = TensorCheckerConfig(enable=True)
    elif not value:
        _active_config = None
    _sync_hook()


from ..core.flags import observe_flag as _observe  # noqa: E402

_observe("FLAGS_check_nan_inf", _on_nan_inf_flag)


def check_numerics(tensor, op_name: str = "tensor", debug_mode=None):
    """Raise (or warn) if tensor contains NaN/Inf (check_numerics analog).
    No-op (returns True) on traced values — value checks are eager-only."""
    import jax
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    arr = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    if isinstance(arr, jax.core.Tracer):
        return True
    if not jnp.issubdtype(arr.dtype, jnp.floating):
        return True
    finite = bool(jnp.all(jnp.isfinite(arr)))
    if not finite:
        n_nan = int(jnp.sum(jnp.isnan(arr)))
        n_inf = int(jnp.sum(jnp.isinf(arr)))
        msg = (f"[check_numerics] op={op_name}: {n_nan} NaN, {n_inf} Inf in "
               f"tensor shape {tuple(arr.shape)} dtype {arr.dtype}")
        mode = debug_mode or (
            _active_config.debug_mode if _active_config
            else DebugMode.CHECK_NAN_INF_AND_ABORT)
        if mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
            raise FloatingPointError(msg)
        import warnings
        warnings.warn(msg)
    return finite


def advance_step():
    """Advance the checker's step counter (drives debug_step windows).
    Called automatically by Optimizer.step(); harmless no-op otherwise."""
    if _active_config is not None:
        _active_config._step += 1


def _dispatch_post_hook(op_name: str, out_arrays):
    """Called from ops.registry dispatch when FLAGS_check_nan_inf or stats
    collection is on. Tracer outputs (ops running inside a jit trace) are
    counted but never concretized — value checks are an eager-mode tool
    (matching the reference's eager nan_inf scan)."""
    import jax

    if _op_stats is not None:
        for a in out_arrays:
            dt = str(getattr(a, "dtype", "other"))
            _op_stats[op_name][dt] += 1
    if _active_config is not None and _active_config._should_check(op_name):
        import jax.numpy as jnp
        for a in out_arrays:
            if isinstance(a, jax.core.Tracer):
                continue  # inside jit: cannot (and must not) concretize
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating):
                if _active_config.output_dir is not None:
                    key = f"{op_name}.{len(_active_config._dump)}"
                    _active_config._dump[key] = np.asarray(a)
                check_numerics(a, op_name,
                               debug_mode=_active_config.debug_mode)


def enable_operator_stats_collection():
    """debugging.py enable_operator_stats_collection analog."""
    global _op_stats
    _op_stats = defaultdict(lambda: defaultdict(int))
    _sync_hook()


def disable_operator_stats_collection():
    """Prints the collected table (reference behavior) and stops."""
    global _op_stats
    if _op_stats is None:
        return
    stats = {k: dict(v) for k, v in _op_stats.items()}
    _op_stats = None
    _sync_hook()
    _print_table(stats)
    return stats


def _print_table(stats):
    dtypes = sorted({dt for per_op in stats.values() for dt in per_op})
    w = max([len(k) for k in stats] + [8])
    header = " " * 2 + "op".ljust(w) + "".join(dt.rjust(12) for dt in dtypes)
    print("<------------------------------ op list "
          "------------------------------->")
    print(header)
    for op in sorted(stats):
        row = " " * 2 + op.ljust(w)
        for dt in dtypes:
            row += str(stats[op].get(dt, 0)).rjust(12)
        print(row)
    print("<------------------------------------------------------------"
          "--------->")


@contextlib.contextmanager
def collect_operator_stats():
    """debugging.py collect_operator_stats analog (context form)."""
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def stats_active() -> bool:
    return _op_stats is not None


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    """debugging.py compare_accuracy analog: compares two run dumps written
    by check_numerics output_dir mode. Minimal offline form: compares two
    .npz dumps tensor-by-tensor and writes a CSV of max abs/rel errors."""
    import csv

    a = np.load(dump_path)
    b = np.load(another_dump_path)
    keys = sorted(set(a.files) & set(b.files))
    with open(output_filename, "w", newline="") as f:
        wr = csv.writer(f)
        wr.writerow(["tensor", "max_abs_err", "max_rel_err"])
        for k in keys:
            x, y = a[k].astype(np.float64), b[k].astype(np.float64)
            abs_err = float(np.max(np.abs(x - y))) if x.shape == y.shape \
                else float("nan")
            rel = abs_err / (float(np.max(np.abs(x))) + 1e-12)
            wr.writerow([k, abs_err, rel])
    return output_filename


__all__ = ["DebugMode", "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "check_numerics",
           "enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats",
           "compare_accuracy"]


def check_layer_numerics(func):
    """ref amp/debugging.py check_layer_numerics: decorator over a layer's
    forward that checks inputs/outputs for NaN/Inf when the tensor checker
    is active."""
    import functools

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        # checks (each a device->host sync) only run while the tensor
        # checker is enabled — the decorator is free otherwise
        if _active_config is None:
            return func(self, *args, **kwargs)

        import numpy as np

        from ..core.tensor import Tensor

        import jax

        def _check(tag, xs):
            for x in xs:
                if isinstance(x, Tensor):
                    if isinstance(x._data, jax.core.Tracer):
                        # under jit tracing a host transfer would raise;
                        # compiled-path NaN checking is the dispatch-level
                        # FLAGS_check_nan_inf hook's job
                        continue
                    arr = np.asarray(x._data)
                    if not np.isfinite(arr).all():
                        raise RuntimeError(
                            f"check_layer_numerics: non-finite values in "
                            f"{tag} of {type(self).__name__}")
        _check("inputs", list(args) + list(kwargs.values()))
        out = func(self, *args, **kwargs)
        _check("outputs", out if isinstance(out, (list, tuple)) else [out])
        return out

    return wrapper


__all__.append("check_layer_numerics")
