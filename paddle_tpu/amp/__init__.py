"""Automatic mixed precision.

Analog of the reference AMP stack: paddle.amp.auto_cast
(python/paddle/amp/auto_cast.py:703, levels O0/OD/O1/O2 at :333), per-op
white/black lists (amp/amp_lists.py), GradScaler with dynamic loss scaling
(amp/grad_scaler.py), and the AMP cast injected into every generated eager
ad_func (eager_gen.py:251). Here the cast policy is applied centrally in the op
dispatch wrapper (ops/registry.py) — on TPU the natural AMP dtype is bfloat16,
which needs no loss scaling, but GradScaler is provided for float16 parity.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor

# Per-op lists mirroring python/paddle/amp/amp_lists.py.
WHITE_LIST = {
    "matmul", "mm", "bmm", "einsum", "conv1d", "conv2d", "conv3d",
    "conv2d_transpose", "linear", "addmm", "scaled_dot_product_attention",
    "flash_attention",
    # matmul-dominated fused blocks (fp32-sensitive pieces inside them —
    # rmsnorm reductions, softmax — already accumulate in fp32)
    "llama_scanned_layers",
}
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "pow", "square", "sqrt", "rsqrt",
    "softmax_with_cross_entropy", "cross_entropy", "log_softmax",
    "mean_all", "reduce_sum_all", "cumsum", "erf", "erfinv",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh_shrink",
    "norm", "p_norm", "cos_sim", "layer_norm_fp32",
}

_STATE = threading.local()


def _stack():
    if not hasattr(_STATE, "stack"):
        _STATE.stack = []
    return _STATE.stack


class _AmpState:
    __slots__ = ("enable", "dtype", "level", "white", "black")

    def __init__(self, enable, dtype, level, white, black):
        self.enable = enable
        self.dtype = dtype
        self.level = level
        self.white = white
        self.black = black


class auto_cast:
    """paddle.amp.auto_cast analog (auto_cast.py:703)."""

    def __init__(self, enable=True, custom_white_list=None, custom_black_list=None,
                 level="O1", dtype="bfloat16"):
        if level not in ("O0", "OD", "O1", "O2"):
            raise ValueError(f"bad amp level {level}")
        white = set(WHITE_LIST)
        black = set(BLACK_LIST)
        if custom_white_list:
            white |= set(custom_white_list)
            black -= set(custom_white_list)
        if custom_black_list:
            black |= set(custom_black_list)
            white -= set(custom_black_list)
        self._st = _AmpState(enable and level != "O0", dtype_mod.to_jax_dtype(dtype),
                             level, white, black)

    def __enter__(self):
        _stack().append(self._st)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


amp_guard = auto_cast  # legacy alias (paddle.base.dygraph.amp_guard)


def amp_state() -> Optional[_AmpState]:
    s = _stack()
    return s[-1] if s else None


def autocast_args(op_name, args, kwargs):
    """Apply the active cast policy to Tensor args. Called from op dispatch."""
    st = amp_state()
    if st is None or not st.enable or getattr(_STATE, "in_cast", False):
        return args, kwargs
    if st.level in ("O1", "OD"):
        if op_name in st.white:
            target = st.dtype
        elif op_name in st.black:
            target = jnp.float32
        else:
            return args, kwargs
    else:  # O2: everything low precision except black list
        target = jnp.float32 if op_name in st.black else st.dtype

    def cast_leaf(x):
        if isinstance(x, Tensor) and jnp.issubdtype(x.dtype, jnp.floating) \
                and x.dtype != target:
            return _guarded_cast(x, target)
        return x

    import jax
    flat, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
    flat = [cast_leaf(x) for x in flat]
    return jax.tree_util.tree_unflatten(treedef, flat)


def _guarded_cast(t: Tensor, target):
    from ..ops import cast
    _STATE.in_cast = True
    try:
        return cast(t, target)
    finally:
        _STATE.in_cast = False


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate analog (auto_cast.py): casts model params to the amp
    dtype for O2 and enables optimizer master weights."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    target = dtype_mod.to_jax_dtype(dtype)
    if level == "O2":
        for m in model_list:
            for p in m.parameters():
                if jnp.issubdtype(p.dtype, jnp.floating):
                    p._set_data(p._data.astype(target))
        if optimizers is not None:
            opts = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
            for o in opts:
                o._multi_precision = True if master_weight is None else master_weight
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list), optimizers


class GradScaler:
    """Dynamic loss scaling (amp/grad_scaler.py analog). bf16 on TPU does not
    need scaling; enable only for float16 experiments."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5,
                 incr_every_n_steps=2000, decr_every_n_nan_or_inf=1,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled_opts = set()

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return self._scale

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable or id(optimizer) in self._unscaled_opts:
            return
        self._unscaled_opts.add(id(optimizer))
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is not None:
                g = p.grad._data * inv
                finite = bool(jnp.all(jnp.isfinite(g)))
                if not finite:
                    found = True
                p.grad = Tensor(g)
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)  # no-op if user already unscaled this opt
        if not self._found_inf:
            optimizer.step()
        self._unscaled_opts.discard(id(optimizer))

    def update(self):
        if not self._enable or not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def minimize(self, optimizer, loss):
        self.step(optimizer)
        self.update()

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps, "enable": self._enable}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state["good_steps"]
        self._bad_steps = state["bad_steps"]


# paddle.amp.debugging (op stats + NaN/Inf checker); imported late so the
# dispatch hook only pays when enabled
from . import debugging  # noqa: E402,F401


def is_float16_supported(device=None):
    """ref amp.is_float16_supported: fp16 compute support. TPUs compute in
    bf16 natively; fp16 works via XLA but without MXU benefit."""
    import jax
    return jax.devices()[0].platform in ("tpu", "gpu", "axon")


def is_bfloat16_supported(device=None):
    """ref amp.is_bfloat16_supported: always true on TPU/XLA backends."""
    return True
