"""paddle.signal namespace — STFT/ISTFT.

Reference: python/paddle/signal.py (frame/overlap_add kernels in
phi/kernels/funcs/frame_functor.h). TPU-native: framing is a gather that XLA
turns into strided slices, the FFT is a batched fft HLO, and overlap-add is a
segment-sum scatter — the whole transform stays on-device.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ops.registry import defop


@defop(name="frame_op")
def _frame(x, frame_length, hop_length, axis=-1):
    if axis not in (-1, x.ndim - 1):
        raise NotImplementedError("frame: only axis=-1 supported")
    n = x.shape[-1]
    num_frames = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(num_frames) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]
    return x[..., idx]  # [..., num_frames, frame_length]


@defop(name="overlap_add_op")
def _overlap_add(frames, hop_length, axis=-1):
    # frames [..., num_frames, frame_length] -> [..., output_len]
    num_frames, frame_length = frames.shape[-2], frames.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    starts = jnp.arange(num_frames) * hop_length
    idx = (starts[:, None] + jnp.arange(frame_length)[None, :]).reshape(-1)
    flat = frames.reshape(frames.shape[:-2] + (-1,))
    out = jnp.zeros(frames.shape[:-2] + (out_len,), dtype=frames.dtype)
    return out.at[..., idx].add(flat)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    return _frame(x, frame_length, hop_length, axis=axis)


def overlap_add(x, hop_length, axis=-1, name=None):
    return _overlap_add(x, hop_length, axis=axis)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """signal.py stft analog: [B, T] -> [B, n_fft//2+1 (or n_fft), frames]."""
    from .. import fft as fft_mod
    from ..core.tensor import Tensor
    from ..ops.registry import dispatch

    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def _impl(sig, win):
        s = sig
        if center:
            pad = n_fft // 2
            cfg = [(0, 0)] * (s.ndim - 1) + [(pad, pad)]
            s = jnp.pad(s, cfg, mode=pad_mode)
        frames = _frame.raw_fn(s, n_fft, hop_length)
        if win is not None:
            w = win
            if win_length < n_fft:  # center the window in the frame
                lp = (n_fft - win_length) // 2
                w = jnp.pad(w, (lp, n_fft - win_length - lp))
            frames = frames * w
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, dtype=spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, frames]

    win_arr = window if window is not None else None
    return dispatch(_impl, (x, win_arr), {}, op_name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """signal.py istft analog (least-squares overlap-add inversion)."""
    from ..ops.registry import dispatch

    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def _impl(spec, win):
        s = jnp.swapaxes(spec, -1, -2)  # [..., frames, freq]
        if normalized:
            s = s * jnp.sqrt(jnp.asarray(n_fft, dtype=s.real.dtype))
        frames = (jnp.fft.irfft(s, n=n_fft, axis=-1) if onesided
                  else jnp.fft.ifft(s, axis=-1).real)
        if win is not None:
            w = win
            if win_length < n_fft:
                lp = (n_fft - win_length) // 2
                w = jnp.pad(w, (lp, n_fft - win_length - lp))
        else:
            w = jnp.ones((n_fft,), dtype=frames.dtype)
        sig = _overlap_add.raw_fn(frames * w, hop_length)
        wsq = _overlap_add.raw_fn(
            jnp.broadcast_to(w * w, frames.shape), hop_length)
        sig = sig / jnp.maximum(wsq, 1e-11)
        if center:
            pad = n_fft // 2
            sig = sig[..., pad:sig.shape[-1] - pad]
        if length is not None:
            sig = sig[..., :length]
        return sig

    win_arr = window if window is not None else None
    return dispatch(_impl, (x, win_arr), {}, op_name="istft")


__all__ = ["stft", "istft", "frame", "overlap_add"]
