"""paddle.linalg namespace.

Reference: python/paddle/linalg.py — re-exports the linear-algebra op family
(implemented here in ops/linalg.py as jax/XLA emissions; on TPU these lower
to MXU matmuls + the XLA decomposition library).
"""
from __future__ import annotations

from ..ops.linalg import (cholesky, cond, corrcoef, cov, det, eig, eigh,
                          eigvals, eigvalsh, householder_product, inverse,
                          lstsq, lu, matrix_exp, matrix_norm, matrix_power,
                          matrix_rank, multi_dot, norm, ormqr, pca_lowrank,
                          pinv, qr, slogdet, solve, svd, svd_lowrank,
                          svdvals, triangular_solve, vector_norm)
from ..ops.linalg import cholesky_solve, lu_unpack

inv = inverse

__all__ = ["cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det",
           "eig", "eigh", "eigvals", "eigvalsh", "householder_product",
           "inv", "inverse", "lstsq", "lu", "lu_unpack", "matrix_exp",
           "matrix_norm", "matrix_power", "matrix_rank", "multi_dot", "norm",
           "ormqr", "pca_lowrank", "pinv", "qr", "slogdet", "solve", "svd",
           "svd_lowrank", "svdvals", "triangular_solve", "vector_norm"]
