"""paddle.regularizer (python/paddle/regularizer.py analog).

L1Decay/L2Decay attach to ParamAttr or an optimizer's weight_decay; the
optimizer applies them as grad += coeff * sign(p) / coeff * p at update
(matching the reference's append_regularization_ops semantics)."""
from __future__ import annotations


class WeightDecayRegularizer:
    def __call__(self, param, grad):
        raise NotImplementedError


class L1Decay(WeightDecayRegularizer):
    """regularizer.py:46 — loss += coeff * sum|w| (grad: coeff*sign(w))."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
        self._regularization_coeff = self.coeff

    def __call__(self, param, grad):
        import jax.numpy as jnp
        return grad + self.coeff * jnp.sign(param)

    def __repr__(self):
        return f"L1Decay, coeff={self.coeff}"


class L2Decay(WeightDecayRegularizer):
    """regularizer.py:159 — loss += 0.5*coeff*sum w^2 (grad: coeff*w)."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
        self._regularization_coeff = self.coeff

    def __call__(self, param, grad):
        return grad + self.coeff * param

    def __repr__(self):
        return f"L2Decay, coeff={self.coeff}"


__all__ = ["L1Decay", "L2Decay", "WeightDecayRegularizer"]
