"""Roofline attribution: WHY is MFU where it is, not just what it is.

``ROOFLINE.json`` (tools/roofline.py output committed at the repo root)
models each bench config's compute time (``t_compute_ms``), HBM time
(``t_memory_ms``), binding resource, and the MFU ceiling the roofline
permits (``measured_mfu_ceiling``). This module JOINS live step timings
against those bounds and publishes the explanation as gauges:

  * ``roofline.observed_mfu``   — the MFU the caller measured;
  * ``roofline.mfu_ceiling``    — what the matched config's roofline
                                  says is attainable;
  * ``roofline.mfu_gap``        — ceiling minus observed: the number a
                                  perf round is supposed to shrink;
  * ``roofline.bound``          — 0 = compute-bound, 1 = memory-bound;
  * ``roofline.gap_attribution{phase=...}`` — the observed step time
    split into ``compute`` (roofline-mandated MXU time), ``memory``
    (HBM time EXPOSED beyond compute overlap), and ``overhead``
    (everything the roofline does not mandate: host gaps, dispatch,
    recompiles — the attackable fraction), each as a fraction of the
    observed step;
  * ``roofline.serving.tokens_per_s`` / ``roofline.serving.bound_frac``
    — serving decode throughput vs the config's token bound.

Attribution scales the config's per-step bounds by the caller's token
count, so a different batch/seq still attributes sensibly; on a CPU
proxy the overhead fraction is honestly ~1.0 (the roofline models the
TPU). Missing/unreadable ROOFLINE.json degrades to a silent no-op —
attribution must never take down a train step.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

__all__ = ["load_roofline", "match_config", "observe_train_step",
           "observe_serving_step", "roofline_path"]

_LOCK = threading.Lock()
_CACHE: Dict[str, object] = {}


def roofline_path() -> str:
    """``PADDLE_ROOFLINE`` env override, else the repo-root file."""
    env = os.environ.get("PADDLE_ROOFLINE")
    if env:
        return env
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "ROOFLINE.json")


def load_roofline(path: Optional[str] = None) -> Optional[dict]:
    """Parse (and cache) the roofline model; None when unavailable."""
    p = path or roofline_path()
    with _LOCK:
        if p in _CACHE:
            return _CACHE[p]  # type: ignore[return-value]
        try:
            with open(p) as f:
                data = json.load(f)
            if not isinstance(data.get("configs"), list) \
                    or not data["configs"]:
                data = None
        except (OSError, ValueError):
            data = None
        _CACHE[p] = data
        return data


def clear_cache() -> None:
    with _LOCK:
        _CACHE.clear()


def match_config(roofline: dict, params: Optional[int] = None,
                 name: Optional[str] = None) -> dict:
    """Pick the config entry to attribute against: explicit name (or
    ``PADDLE_ROOFLINE_CONFIG``) wins, else nearest by param count, else
    the first entry."""
    configs: List[dict] = roofline["configs"]
    name = name or os.environ.get("PADDLE_ROOFLINE_CONFIG")
    if name:
        for c in configs:
            if c.get("config") == name:
                return c
    if params:
        return min(configs,
                   key=lambda c: abs(c.get("params", 0) - params))
    return configs[0]


def _gauges():
    from .metrics import get_registry
    reg = get_registry()
    return {
        "observed": reg.gauge("roofline.observed_mfu",
                              "measured model FLOPs utilization"),
        "ceiling": reg.gauge("roofline.mfu_ceiling",
                             "roofline-attainable MFU of the matched "
                             "config"),
        "gap": reg.gauge("roofline.mfu_gap",
                         "mfu_ceiling minus observed_mfu — the "
                         "closable distance"),
        "bound": reg.gauge("roofline.bound",
                           "binding resource of the matched config "
                           "(0=compute, 1=memory)"),
        "attr": reg.gauge("roofline.gap_attribution",
                          "observed step time split by phase "
                          "(fraction of the step)",
                          labelnames=("phase",)),
    }


def observe_train_step(step_s: float, observed_mfu: float,
                       tokens: Optional[int] = None,
                       params: Optional[int] = None,
                       config: Optional[str] = None,
                       comm_bytes_by_axis: Optional[Dict[str, float]] = None
                       ) -> Optional[dict]:
    """Join one train-step timing against the roofline; publish gauges.

    ``comm_bytes_by_axis`` (from the SPMD mesh plan: analytic per-step
    collective bytes by mesh axis) splits the overhead fraction further
    into per-axis communication phases ``comm:{axis}``, each priced at
    the roofline's interconnect bandwidth — overhead then means "not
    compute, not HBM, not mandated collectives". Omitted (every
    single-chip caller), the published series are exactly the previous
    three phases — byte-identical output.

    Returns the attribution dict (also useful to callers/tests), or
    None when no roofline model is available.
    """
    roofline = load_roofline()
    if roofline is None or step_s <= 0:
        return None
    cfg = match_config(roofline, params=params, name=config)
    ceiling = float(cfg.get("measured_mfu_ceiling", 1.0))
    t_compute = float(cfg.get("t_compute_ms", 0.0)) / 1e3
    t_memory = float(cfg.get("t_memory_ms", 0.0)) / 1e3
    cfg_tokens = max(1, int(cfg.get("batch", 1)) * int(cfg.get("seq", 1)))
    scale = (tokens / cfg_tokens) if tokens else 1.0
    # roofline-mandated times for THIS step's token count
    tc, tm = t_compute * scale, t_memory * scale
    t_ideal = max(tc, tm)
    compute_frac = min(1.0, tc / step_s)
    memory_frac = min(1.0 - compute_frac, max(0.0, tm - tc) / step_s)
    overhead_frac = max(0.0, (step_s - t_ideal) / step_s)
    comm_fracs: Dict[str, float] = {}
    if comm_bytes_by_axis:
        from ..analysis.sharding import ici_bytes_per_s
        bw = ici_bytes_per_s(roofline)
        for axis, nb in sorted(comm_bytes_by_axis.items()):
            if bw <= 0 or nb <= 0:
                continue
            # mandated comm time, capped by what overhead has left
            frac = min(max(0.0, overhead_frac), (nb / bw) / step_s)
            comm_fracs[axis] = frac
            overhead_frac = max(0.0, overhead_frac - frac)
    g = _gauges()
    g["observed"].set(float(observed_mfu))
    g["ceiling"].set(ceiling)
    g["gap"].set(ceiling - float(observed_mfu))
    g["bound"].set(1.0 if cfg.get("bound") == "memory" else 0.0)
    g["attr"].labels(phase="compute").set(compute_frac)
    g["attr"].labels(phase="memory").set(memory_frac)
    g["attr"].labels(phase="overhead").set(overhead_frac)
    for axis, frac in comm_fracs.items():
        g["attr"].labels(phase=f"comm:{axis}").set(frac)
    out = {"config": cfg.get("config"), "mfu_ceiling": ceiling,
           "mfu_gap": ceiling - float(observed_mfu),
           "bound": cfg.get("bound"),
           "compute_frac": compute_frac, "memory_frac": memory_frac,
           "overhead_frac": overhead_frac}
    if comm_fracs:
        out["comm_fracs"] = comm_fracs
    try:
        # refine phases into per-op-class gauges when the opprof
        # observatory holds a train-step capture (no-op otherwise; the
        # same must-never-take-down-a-step contract as above)
        from . import opprof as _opprof
        _opprof.publish_gap_attribution(out)
    except Exception:
        pass
    return out


def observe_serving_step(step_s: float, tokens: int,
                         config: Optional[str] = None) -> None:
    """Join one decode dispatch against the config's token-rate bound.

    ``roofline.serving.bound_frac`` is observed decode tokens/s over the
    roofline's ``tokens_per_s_bound`` — how much of the modeled ceiling
    serving actually achieves (CPU proxies read near 0; that is the
    honest answer).
    """
    if step_s <= 0 or tokens <= 0:
        return
    roofline = load_roofline()
    if roofline is None:
        return
    cfg = match_config(roofline, name=config)
    bound = float(cfg.get("tokens_per_s_bound", 0.0))
    rate = tokens / step_s
    from .metrics import get_registry
    reg = get_registry()
    reg.gauge("roofline.serving.tokens_per_s",
              "decode tokens/sec of the latest serving dispatch"
              ).set(rate)
    if bound > 0:
        reg.gauge("roofline.serving.bound_frac",
                  "serving decode rate over the roofline token bound"
                  ).set(rate / bound)
