"""SLO burn-rate monitoring over the serving latency histograms.

SRE-workbook style multi-window alerting: an SLO ("99% of requests see
TTFT <= 0.5 s") defines an error budget (1 - objective); the BURN RATE
is the observed error rate divided by that budget (burn 1.0 = exactly
spending the budget over the SLO period; burn 14.4 = the budget gone in
1/14.4 of it). An alert fires only when BOTH a fast and a slow window
burn above the threshold — the fast window gives low detection latency,
the slow window keeps a short blip from paging.

The monitor is PULL-based over the cumulative histograms the gateway
already populates (``gateway.ttft_seconds`` / ``gateway.tpot_seconds``):
each ``poll()`` snapshots (total, good-within-threshold) per SLO into a
bounded ring, and window rates are deltas between snapshots — no second
event pipe, no per-request cost. Good-count comes from the histogram's
bucket counts, so thresholds should sit on a bucket bound (the default
latency ladder covers the usual SLO points).

Clock-injectable (tests replay deterministically); alerts are typed
``Alert`` records kept on the monitor AND counted in the registry
(``slo.alerts_total{slo,severity}``), with live burn gauges
(``slo.burn_rate{slo,window}``) for dashboards.

Alerts are edge-triggered, and the OTHER edge is typed too: when a
firing condition's burn rate falls back under threshold, the monitor
emits a ``Resolved`` record (kept on ``.resolutions``, counted in
``slo.resolved_total{slo,severity}``, carrying the incident duration) —
so a consumer like the auto-remediator can distinguish an ongoing
incident from a recovered one instead of inferring recovery from
silence.
"""
from __future__ import annotations

import bisect
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .metrics import Histogram, get_registry

__all__ = ["SLO", "BurnWindow", "Alert", "Resolved", "SLOMonitor",
           "default_gateway_slos", "DEFAULT_WINDOWS"]


@dataclass(frozen=True)
class SLO:
    """latency objective: ``objective`` of requests complete within
    ``threshold_s`` on the histogram series ``metric``."""

    name: str
    metric: str
    threshold_s: float
    objective: float = 0.99

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), "
                             f"got {self.objective}")
        if self.threshold_s <= 0:
            raise ValueError("threshold_s must be positive")

    @property
    def budget(self) -> float:
        return 1.0 - self.objective


@dataclass(frozen=True)
class BurnWindow:
    """One multi-window burn-rate rule: alert when both the fast and the
    slow window burn at >= ``burn_threshold``."""

    fast_s: float
    slow_s: float
    burn_threshold: float
    severity: str = "page"


# the SRE-workbook defaults (1h/5m page at 14.4x, 6h/30m ticket at 6x),
# fast window listed first
DEFAULT_WINDOWS: Tuple[BurnWindow, ...] = (
    BurnWindow(fast_s=300.0, slow_s=3600.0, burn_threshold=14.4,
               severity="page"),
    BurnWindow(fast_s=1800.0, slow_s=21600.0, burn_threshold=6.0,
               severity="ticket"),
)


@dataclass
class Alert:
    """One fired burn-rate alert (typed record, kept on the monitor)."""

    slo: str
    severity: str
    burn_fast: float
    burn_slow: float
    fast_window_s: float
    slow_window_s: float
    fired_at: float
    message: str = ""


@dataclass
class Resolved:
    """The recovery edge of a previously fired alert: the burn rate fell
    back under threshold. ``duration_s`` spans fired_at → resolved_at."""

    slo: str
    severity: str
    fired_at: float
    resolved_at: float
    duration_s: float
    message: str = ""


def default_gateway_slos(ttft_s: float = 0.5, tpot_s: float = 0.1,
                         objective: float = 0.99) -> List[SLO]:
    """The two SLOs the gateway's admission control already speaks."""
    return [SLO("gateway_ttft", "gateway.ttft_seconds", ttft_s,
                objective),
            SLO("gateway_tpot", "gateway.tpot_seconds", tpot_s,
                objective)]


@dataclass
class _Snap:
    t: float
    total: int
    good: int


class SLOMonitor:
    """Multi-window burn-rate evaluation over registry histograms."""

    def __init__(self, slos: Sequence[SLO],
                 windows: Sequence[BurnWindow] = DEFAULT_WINDOWS,
                 registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 max_snapshots: int = 4096):
        if not slos:
            raise ValueError("need at least one SLO")
        self.slos = list(slos)
        self.windows = list(windows)
        self._reg = registry or get_registry()
        self._clock = clock
        self._snaps: Dict[str, deque] = {
            s.name: deque(maxlen=max_snapshots) for s in self.slos}
        self.alerts: List[Alert] = []
        self.resolutions: List[Resolved] = []
        # (slo, severity) currently firing → the alert's fired_at time
        # (a dict so the resolution edge can report incident duration)
        self._active: Dict[Tuple[str, str], float] = {}
        self._burn_g = self._reg.gauge(
            "slo.burn_rate", "error-budget burn rate by SLO and window",
            labelnames=("slo", "window"))
        self._alerts_c = self._reg.counter(
            "slo.alerts_total", "burn-rate alerts fired",
            labelnames=("slo", "severity"))
        self._resolved_c = self._reg.counter(
            "slo.resolved_total", "burn-rate alerts resolved",
            labelnames=("slo", "severity"))

    # -- histogram reading ----------------------------------------------------
    def _counts(self, slo: SLO) -> Tuple[int, int]:
        """(total, good-within-threshold) from the cumulative histogram;
        label families sum across children."""
        entry = self._reg.get(slo.metric)
        if entry is None:
            return 0, 0
        children = (entry.children() if hasattr(entry, "children")
                    else [entry])
        total = good = 0
        for h in children:
            if not isinstance(h, Histogram):
                continue
            counts = h.bucket_counts()
            # bucket i counts observations v with v <= buckets[i] (and
            # > buckets[i-1]); good = every bucket whose bound fits
            k = bisect.bisect_right(h.buckets, slo.threshold_s + 1e-12)
            total += sum(counts)
            good += sum(counts[:k])
        return total, good

    # -- window arithmetic ----------------------------------------------------
    @staticmethod
    def _at_or_before(snaps: deque, t: float) -> Optional[_Snap]:
        """Newest snapshot taken at or before ``t`` (None if all are
        newer — then the oldest is the best available partial window)."""
        best = None
        for s in snaps:
            if s.t <= t:
                best = s
            else:
                break
        return best

    def _error_rate(self, snaps: deque, window_s: float,
                    now: float) -> float:
        cur = snaps[-1]
        base = self._at_or_before(snaps, now - window_s) or snaps[0]
        d_total = cur.total - base.total
        if d_total <= 0:
            return 0.0
        d_bad = d_total - (cur.good - base.good)
        return max(0.0, d_bad / d_total)

    # -- the evaluation tick --------------------------------------------------
    def poll(self, now: Optional[float] = None) -> List[Alert]:
        """Snapshot every SLO's histogram and evaluate all burn windows.
        Returns alerts that fired DURING THIS CALL (edge-triggered: an
        alert re-fires only after its condition clears and re-arms)."""
        now = self._clock() if now is None else now
        fired: List[Alert] = []
        for slo in self.slos:
            snaps = self._snaps[slo.name]
            total, good = self._counts(slo)
            snaps.append(_Snap(now, total, good))
            for w in self.windows:
                burn_fast = self._error_rate(snaps, w.fast_s,
                                             now) / slo.budget
                burn_slow = self._error_rate(snaps, w.slow_s,
                                             now) / slo.budget
                self._burn_g.labels(
                    slo=slo.name,
                    window=f"{int(w.fast_s)}s").set(burn_fast)
                self._burn_g.labels(
                    slo=slo.name,
                    window=f"{int(w.slow_s)}s").set(burn_slow)
                key = (slo.name, w.severity)
                if burn_fast >= w.burn_threshold \
                        and burn_slow >= w.burn_threshold:
                    if key not in self._active:
                        self._active[key] = now
                        alert = Alert(
                            slo=slo.name, severity=w.severity,
                            burn_fast=burn_fast, burn_slow=burn_slow,
                            fast_window_s=w.fast_s, slow_window_s=w.slow_s,
                            fired_at=now,
                            message=(f"{slo.name}: burning "
                                     f"{burn_fast:.1f}x budget over "
                                     f"{int(w.fast_s)}s and "
                                     f"{burn_slow:.1f}x over "
                                     f"{int(w.slow_s)}s (threshold "
                                     f"{w.burn_threshold}x, objective "
                                     f"{slo.objective})"))
                        self.alerts.append(alert)
                        fired.append(alert)
                        self._alerts_c.labels(
                            slo=slo.name, severity=w.severity).inc()
                else:
                    fired_at = self._active.pop(key, None)
                    if fired_at is not None:
                        # recovery edge: the condition re-arms AND the
                        # incident closes as a typed record
                        res = Resolved(
                            slo=slo.name, severity=w.severity,
                            fired_at=fired_at, resolved_at=now,
                            duration_s=now - fired_at,
                            message=(f"{slo.name}: burn back under "
                                     f"{w.burn_threshold}x after "
                                     f"{now - fired_at:.1f}s"))
                        self.resolutions.append(res)
                        self._resolved_c.labels(
                            slo=slo.name, severity=w.severity).inc()
        return fired

    def summary(self) -> dict:
        """Current state for dashboards / ``telemetry_dump --slo``."""
        out: dict = {"slos": [], "alerts": [a.__dict__ for a in
                                            self.alerts],
                     "resolutions": [r.__dict__ for r in
                                     self.resolutions]}
        for slo in self.slos:
            snaps = self._snaps[slo.name]
            cur = snaps[-1] if snaps else None
            burns = {}
            if cur is not None:
                for w in self.windows:
                    burns[f"{int(w.fast_s)}s"] = self._error_rate(
                        snaps, w.fast_s, cur.t) / slo.budget
                    burns[f"{int(w.slow_s)}s"] = self._error_rate(
                        snaps, w.slow_s, cur.t) / slo.budget
            out["slos"].append({
                "name": slo.name, "metric": slo.metric,
                "threshold_s": slo.threshold_s,
                "objective": slo.objective,
                "total": cur.total if cur else 0,
                "good": cur.good if cur else 0,
                "burn_rates": burns,
                "firing": sorted(sev for (n, sev) in self._active
                                 if n == slo.name),
            })
        return out
