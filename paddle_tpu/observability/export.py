"""Telemetry exporters: Prometheus text format + JSONL snapshots.

Both render the plain-dict series produced by ``MetricsRegistry.snapshot``
so a snapshot written in one process (``write_jsonl``) re-renders in
another (``tools/telemetry_dump.py --snapshot``) byte-for-value identical
— the round trip tests pin that.
"""
from __future__ import annotations

import json
import os
import re
import time
from typing import List, Optional

from .metrics import MetricsRegistry, get_registry

__all__ = ["render_prometheus", "write_jsonl", "load_jsonl",
           "snapshot_series"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def snapshot_series(registry: Optional[MetricsRegistry] = None,
                    include_native: bool = True) -> List[dict]:
    return (registry or get_registry()).snapshot(
        include_native=include_native)


def _san(name: str) -> str:
    """Prometheus metric-name sanitizer (dots etc. -> underscores)."""
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _labelstr(labels: dict, extra: Optional[dict] = None) -> str:
    merged = dict(labels or {})
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{_san(k)}="{_esc(v)}"'
                     for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def _fmt(v) -> str:
    if v is None:
        return "NaN"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(series: Optional[List[dict]] = None,
                      registry: Optional[MetricsRegistry] = None) -> str:
    """Prometheus text exposition of a snapshot (or the live registry).

    Histograms render the standard cumulative ``_bucket{le=...}`` series
    plus ``_sum``/``_count``; the reservoir quantile estimates ride along
    as a separate ``<name>_quantile`` gauge family (mixing summary-style
    quantile lines into a histogram family is invalid exposition).
    """
    if series is None:
        series = snapshot_series(registry)
    # group by (name, type) so HELP/TYPE headers emit once per family
    by_family: dict = {}
    for s in series:
        by_family.setdefault((s["name"], s["type"]), []).append(s)
    lines: List[str] = []
    for (name, kind), members in by_family.items():
        pname = _san(name)
        lines.append(f"# TYPE {pname} {kind}")
        for s in members:
            labels = s.get("labels") or {}
            if kind == "histogram":
                cum = 0
                bounds = list(s.get("buckets") or [])
                counts = list(s.get("bucket_counts") or [])
                for bound, c in zip(bounds, counts):
                    cum += c
                    lines.append(
                        f"{pname}_bucket"
                        f"{_labelstr(labels, {'le': _fmt(bound)})} {cum}")
                cum += counts[len(bounds)] if len(counts) > len(bounds) else 0
                lines.append(
                    f"{pname}_bucket{_labelstr(labels, {'le': '+Inf'})} "
                    f"{cum}")
                lines.append(f"{pname}_sum{_labelstr(labels)} "
                             f"{_fmt(s.get('sum', 0.0))}")
                lines.append(f"{pname}_count{_labelstr(labels)} "
                             f"{s.get('count', 0)}")
                for qname, qv in (s.get("quantiles") or {}).items():
                    if qv is None:
                        continue
                    q = {"p50": "0.5", "p95": "0.95", "p99": "0.99"}.get(
                        qname, qname)
                    lines.append(
                        f"{pname}_quantile"
                        f"{_labelstr(labels, {'quantile': q})} {_fmt(qv)}")
            else:
                lines.append(
                    f"{pname}{_labelstr(labels)} {_fmt(s.get('value'))}")
                if kind == "gauge" and "peak" in s:
                    lines.append(
                        f"{pname}_peak{_labelstr(labels)} "
                        f"{_fmt(s.get('peak'))}")
    return "\n".join(lines) + ("\n" if lines else "")


def write_jsonl(path: str, registry: Optional[MetricsRegistry] = None,
                series: Optional[List[dict]] = None,
                extra: Optional[dict] = None) -> str:
    """One JSON object per line: a meta header, then every series.

    Atomic replace — a mid-write kill must not leave a truncated snapshot
    where a previous good one stood.
    """
    if series is None:
        series = snapshot_series(registry)
    meta = {"__meta__": {
        "format": "paddle_tpu.observability/1",
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "series": len(series)}}
    if extra:
        meta["__meta__"].update(extra)
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(meta) + "\n")
        for s in series:
            f.write(json.dumps(s) + "\n")
    os.replace(tmp, path)
    return path


def load_jsonl(path: str) -> List[dict]:
    """Read a write_jsonl snapshot back into its series list (meta line
    dropped; corrupt lines raise — a half-snapshot must not parse as a
    smaller healthy one)."""
    series: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if "__meta__" in obj:
                continue
            series.append(obj)
    return series
