"""Crash-surviving flight recorder: a fixed-size binary ring journal.

When chaos ``kill_rank`` (or a real crash) takes a process down with
``os._exit``, everything buffered in userspace dies with it — the
metrics registry, the trace recorder, half-written log lines. This
module is the black box that survives: a pre-allocated fixed-geometry
ring file per rank where every event lands via one unbuffered
``os.pwrite`` (page cache persists across process death; only a kernel
panic loses it), so ``tools/blackbox.py postmortem`` can replay the last
N events of every rank — including the killed one — after the fact.

File layout (all little-endian)::

    header (64 B):  magic "PTFLIGHT" | version u32 | slot_size u32 |
                    nslots u32 | epoch u32 | rank i32 | pad
    slot  (slot_size B, nslots of them):
                    seq u64 | epoch u32 | len u32 | wall_t f64 |
                    crc32 u32 | payload (JSON, truncated to fit)

Appends are O(1): slot index = ``seq % nslots``; no cursor is persisted.
Reopen recovers the cursor by scanning for the max valid seq (O(N) once)
and bumps + fsyncs the epoch header, so events from before and after a
restart stay distinguishable while seq keeps one total order.

Events recorded by the instrumented seams: span open/close
(``trace_context``), collective enter/exit (``distributed.collective``),
chaos injections — written BEFORE the fault executes, so a kill_rank is
the victim's last journal entry (``resilience.chaos``) — and checkpoint
commits (``resilience.checkpoint_manager``).

Armed iff ``PADDLE_TELEMETRY_DIR`` is set (one cached check per event
when disarmed); the ring lives at ``<dir>/flight-rank<r>.ring``.
"""
from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Dict, List, Optional

from ..utils.locks import TracedLock

__all__ = ["FlightRecorder", "get_flight", "flight_record",
           "read_ring", "build_postmortem", "reset_flight"]

_MAGIC = b"PTFLIGHT"
_VERSION = 1
_HDR = struct.Struct("<8sIIIIi")          # magic, ver, slot, nslots, epoch, rank
_HDR_SIZE = 64
_SLOT_HDR = struct.Struct("<QIIdI")       # seq, epoch, len, wall_t, crc
_DEFAULT_SLOTS = 2048
_DEFAULT_SLOT_SIZE = 256


class FlightRecorder:
    """One rank's ring journal (open for appending)."""

    def __init__(self, path: str, slots: int = _DEFAULT_SLOTS,
                 slot_size: int = _DEFAULT_SLOT_SIZE, rank: int = 0):
        if slot_size <= _SLOT_HDR.size + 2:
            raise ValueError(f"slot_size {slot_size} too small")
        self.path = path
        self._lock = TracedLock("FlightRecorder._lock")
        existing = os.path.exists(path) and os.path.getsize(path) >= _HDR_SIZE
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        if existing:
            hdr = os.pread(self._fd, _HDR_SIZE, 0)
            magic, ver, fss, fns, epoch, frank = _HDR.unpack(
                hdr[:_HDR.size])
            if magic != _MAGIC or ver != _VERSION:
                raise ValueError(f"{path}: not a flight ring "
                                 f"(magic={magic!r} ver={ver})")
            # adopt the file's geometry — a reopened ring keeps its shape
            self.slot_size, self.nslots = fss, fns
            self.rank = rank if rank is not None else frank
            self.epoch = epoch + 1
            self._seq = self._recover_seq()
        else:
            self.slot_size, self.nslots = int(slot_size), int(slots)
            self.rank = rank
            self.epoch = 0
            self._seq = 0
            os.ftruncate(self._fd,
                         _HDR_SIZE + self.nslots * self.slot_size)
        self._write_header()          # epoch header, fsync'd

    def _write_header(self):
        hdr = _HDR.pack(_MAGIC, _VERSION, self.slot_size, self.nslots,
                        self.epoch, self.rank)
        os.pwrite(self._fd, hdr.ljust(_HDR_SIZE, b"\0"), 0)
        os.fsync(self._fd)

    def _recover_seq(self) -> int:
        top = 0
        for i in range(self.nslots):
            raw = os.pread(self._fd, _SLOT_HDR.size,
                           _HDR_SIZE + i * self.slot_size)
            if len(raw) < _SLOT_HDR.size:
                continue
            seq, _ep, ln, _t, crc = _SLOT_HDR.unpack(raw)
            if ln == 0 or ln > self.slot_size - _SLOT_HDR.size:
                continue
            top = max(top, seq + 1)
        return top

    @property
    def seq(self) -> int:
        return self._seq

    def record(self, kind: str, wall_t: Optional[float] = None,
               **fields) -> int:
        """Append one event; returns its seq. One pwrite, no fsync —
        page-cache durability is exactly the survive-``os._exit`` bar."""
        import time
        t = time.time() if wall_t is None else wall_t
        cap = self.slot_size - _SLOT_HDR.size
        payload = json.dumps({"kind": kind, **fields},
                             separators=(",", ":")).encode()
        if len(payload) > cap:
            payload = json.dumps(
                {"kind": kind, "truncated": True},
                separators=(",", ":")).encode()[:cap]
        with self._lock:
            seq = self._seq
            self._seq += 1
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        slot = _SLOT_HDR.pack(seq, self.epoch, len(payload), t, crc) \
            + payload
        os.pwrite(self._fd, slot, _HDR_SIZE + (seq % self.nslots)
                  * self.slot_size)
        return seq

    def events(self) -> List[dict]:
        """Every valid event currently in the ring, seq-ordered. Each
        dict carries ``_seq``/``_epoch``/``_t`` bookkeeping beside the
        recorded payload fields."""
        return read_ring(self.path)

    def close(self):
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


def read_ring(path: str) -> List[dict]:
    """Read a ring file (no recorder needed — the post-mortem path).
    Corrupt/empty slots are skipped, never raised: a half-written slot
    from the moment of death must not hide the rest of the journal."""
    out: List[dict] = []
    with open(path, "rb") as f:
        hdr = f.read(_HDR_SIZE)
        if len(hdr) < _HDR.size:
            return out
        magic, ver, slot_size, nslots, epoch, rank = _HDR.unpack(
            hdr[:_HDR.size])
        if magic != _MAGIC or ver != _VERSION:
            raise ValueError(f"{path}: not a flight ring")
        for i in range(nslots):
            f.seek(_HDR_SIZE + i * slot_size)
            raw = f.read(slot_size)
            if len(raw) < _SLOT_HDR.size:
                continue
            seq, ep, ln, t, crc = _SLOT_HDR.unpack(raw[:_SLOT_HDR.size])
            if ln == 0 or ln > slot_size - _SLOT_HDR.size:
                continue
            payload = raw[_SLOT_HDR.size:_SLOT_HDR.size + ln]
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                continue
            try:
                obj = json.loads(payload.decode())
            except ValueError:
                continue
            obj["_seq"] = seq
            obj["_epoch"] = ep
            obj["_t"] = t
            obj["_rank"] = rank
            out.append(obj)
    out.sort(key=lambda e: e["_seq"])
    return out


# -- process-wide recorder (armed by PADDLE_TELEMETRY_DIR) -------------------

_UNPROBED = object()
_REC = _UNPROBED   # _UNPROBED | None (disabled) | FlightRecorder
_REC_LOCK = TracedLock("flight._REC_LOCK")


def _resolve_rank() -> int:
    return int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)


def get_flight() -> Optional[FlightRecorder]:
    """This process's ring (created lazily under PADDLE_TELEMETRY_DIR);
    None when telemetry is disarmed."""
    global _REC
    rec = _REC
    if rec is not _UNPROBED:
        return rec
    with _REC_LOCK:
        if _REC is not _UNPROBED:
            return _REC
        d = os.environ.get("PADDLE_TELEMETRY_DIR")
        if not d:
            _REC = None
            return None
        os.makedirs(d, exist_ok=True)
        rank = _resolve_rank()
        slots = int(os.environ.get("PADDLE_FLIGHT_SLOTS",
                                   str(_DEFAULT_SLOTS)))
        try:
            _REC = FlightRecorder(
                os.path.join(d, f"flight-rank{rank:05d}.ring"),
                slots=slots, rank=rank)
        except OSError:
            _REC = None
        return _REC


def flight_record(kind: str, **fields) -> None:
    """Record an event on this process's ring; no-op when disarmed
    (one cached-global check)."""
    rec = _REC
    if rec is _UNPROBED:
        rec = get_flight()
    if rec is not None:
        rec.record(kind, **fields)


def reset_flight() -> None:
    """Drop the cached recorder so the next event re-probes the env
    (tests re-point PADDLE_TELEMETRY_DIR between cases)."""
    global _REC
    with _REC_LOCK:
        if _REC not in (None, _UNPROBED):
            _REC.close()
        _REC = _UNPROBED


# -- post-mortem reconstruction ----------------------------------------------

def build_postmortem(dirpath: str,
                     last_seconds: Optional[float] = None) -> dict:
    """Replay every surviving ring under `dirpath` into one cross-rank
    record: a wall-clock-ordered timeline plus a per-rank verdict (last
    event, and whether the rank looks like it died mid-collective — an
    unexited ``collective_enter``/``chaos`` as the final entry)."""
    ranks: Dict[int, dict] = {}
    timeline: List[dict] = []
    import glob
    for path in sorted(glob.glob(os.path.join(dirpath,
                                              "flight-rank*.ring"))):
        try:
            events = read_ring(path)
        except (OSError, ValueError) as e:
            ranks[-1] = {"file": path, "error": str(e)}
            continue
        if not events:
            continue
        rank = events[0]["_rank"]
        if last_seconds is not None:
            horizon = max(e["_t"] for e in events) - last_seconds
            events = [e for e in events if e["_t"] >= horizon]
        last = events[-1]
        open_colls = {}
        open_ckpts = {}
        for e in events:
            kind = e.get("kind")
            if kind == "collective_enter":
                open_colls[e.get("seq")] = e
            elif kind == "collective_exit":
                open_colls.pop(e.get("seq"), None)
            elif kind == "ckpt.save_begin":
                open_ckpts[e.get("step")] = e
            elif kind in ("ckpt.shard_ack", "ckpt.commit",
                          "ckpt.ack_timeout"):
                # this rank's part of the save is over (acked, published,
                # or aborted) — only a begin with none of these is torn
                open_ckpts.pop(e.get("step"), None)
        died_in = (last if (last.get("kind") in
                            ("collective_enter", "chaos")
                            or (str(last.get("kind", "")).startswith("ckpt.")
                                and open_ckpts))
                   else None)
        ranks[rank] = {
            "file": path,
            "events": len(events),
            "epochs": sorted({e["_epoch"] for e in events}),
            "last_event": last,
            "open_collectives": sorted(open_colls),
            "open_checkpoints": sorted(open_ckpts),
            "suspect_death": ({"kind": last.get("kind"),
                               "op": last.get("op"),
                               "point": last.get("point"),
                               "fault": last.get("fault"),
                               "step": last.get("step")}
                              if died_in is not None else None),
        }
        timeline.extend(events)
    timeline.sort(key=lambda e: (e["_t"], e["_rank"], e["_seq"]))
    return {"dir": dirpath, "ranks": {str(r): v for r, v
                                      in sorted(ranks.items())},
            "timeline": timeline}
