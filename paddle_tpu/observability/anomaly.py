"""Streaming anomaly detection over per-replica serving latencies.

The fleet plane (``observability.fleet``) diagnoses *collective*
pathologies — stragglers, desyncs, missing ranks — as typed
``FleetFinding``s. This module adds the *serving-side* detectors the
future auto-remediator (ROADMAP item 5) consumes from the SAME stream:
robust EWMA/MAD change detection over per-replica TTFT, TPOT and
queue-depth series, emitting ``FleetFinding``s with kinds
``ttft_spike`` / ``tpot_spike`` / ``queue_depth_spike`` so one consumer
format covers both planes.

Detection is deliberately robust, not Gaussian: the baseline is the
rolling **median**, the scale is the **MAD** (median absolute
deviation, floored at a fraction of the median so a perfectly quiet
series cannot divide by ~zero), and a sample fires only after a warmup
of ``min_samples`` observations. Samples that FIRE enter the baseline
window winsorized (clamped at ``median + 3 * scale``): a persistent
fault cannot absorb itself into its own baseline and go quiet — it
keeps firing until fixed (or until the slow, bounded winsorized
adaptation accepts the new level as normal). An EWMA of the series rides along in
every finding's detail for the remediator's trend view. Everything is
deterministic given the observation sequence — chaos drills assert on
it.

Feeds:

- ``AnomalyDetector.observe(metric, key, value)`` — the raw streaming
  core (any metric name / series key),
- ``AnomalyDetector.observe_waterfalls(wfs)`` — offline: derive
  per-replica TTFT/TPOT observations from reconstructed
  ``observability.waterfall`` waterfalls (trace-only postmortems),
- ``GatewayProbe(gw)`` — online: wraps the gateway pool's
  ``step_replica`` so every engine step feeds a per-replica step-time
  series ("TPOT proxy": one batched step yields one token per active
  request) plus the gateway queue depth, with zero gateway code
  changes.
"""
from __future__ import annotations

import time as _time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from .fleet import FleetFinding

__all__ = ["AnomalyDetector", "GatewayProbe"]

DEFAULT_THRESHOLD = 6.0       # robust z-score that fires a finding
DEFAULT_MIN_SAMPLES = 8       # warmup before a series may fire
DEFAULT_WINDOW = 64           # rolling median/MAD window
DEFAULT_EWMA_ALPHA = 0.3
MAD_FLOOR_FRAC = 0.05         # scale floor: 5% of |median|
WINSOR_SIGMA = 3.0            # firing samples enter the baseline
#                               clamped at med + 3*scale — NOT at the
#                               firing threshold (a high threshold would
#                               make the clamp itself an outlier big
#                               enough to blow up a small window's MAD)


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


class _Track:
    __slots__ = ("window", "ewma", "count")

    def __init__(self, window: int):
        self.window: Deque[float] = deque(maxlen=window)
        self.ewma: Optional[float] = None
        self.count = 0


class AnomalyDetector:
    """Streaming robust spike detector; findings accumulate on
    ``self.findings`` in observation order."""

    def __init__(self, threshold: float = DEFAULT_THRESHOLD,
                 min_samples: int = DEFAULT_MIN_SAMPLES,
                 window: int = DEFAULT_WINDOW,
                 ewma_alpha: float = DEFAULT_EWMA_ALPHA):
        self.threshold = float(threshold)
        self.min_samples = int(min_samples)
        self.window = int(window)
        self.ewma_alpha = float(ewma_alpha)
        self.findings: List[FleetFinding] = []
        self._tracks: Dict[Tuple[str, str], _Track] = {}
        self._seq = 0

    def observe(self, metric: str, key: str,
                value: float) -> Optional[FleetFinding]:
        """Feed one sample of ``metric`` for series ``key`` (a replica
        name, a gateway id...). Returns the finding when this sample is
        anomalous vs the series' own history, else None."""
        value = float(value)
        track = self._tracks.setdefault((metric, key),
                                        _Track(self.window))
        finding = None
        baseline_value = value
        if track.count >= self.min_samples and track.window:
            med = _median(list(track.window))
            mad = _median([abs(x - med) for x in track.window]) * 1.4826
            scale = max(mad, MAD_FLOOR_FRAC * abs(med), 1e-12)
            score = (value - med) / scale
            if score >= self.threshold:
                self._seq += 1
                finding = FleetFinding(
                    kind=f"{metric}_spike", op=metric, seq=self._seq,
                    skew_s=value - med,
                    detail={"key": key, "value": value, "baseline": med,
                            "mad": mad, "score": score,
                            "ewma": track.ewma, "n": track.count})
                self.findings.append(finding)
                # a confirmed outlier must not poison the baseline it
                # was judged against: enter the window WINSORIZED near
                # the baseline, so a persistent fault keeps firing
                # (remediator hysteresis needs consecutive findings)
                # while the baseline still adapts — slowly and boundedly
                baseline_value = med + WINSOR_SIGMA * scale
        track.window.append(baseline_value)
        track.count += 1
        track.ewma = value if track.ewma is None else (
            self.ewma_alpha * value
            + (1.0 - self.ewma_alpha) * track.ewma)
        return finding

    def baseline(self, metric: str, key: str) -> Optional[dict]:
        track = self._tracks.get((metric, key))
        if track is None or not track.window:
            return None
        med = _median(list(track.window))
        return {"median": med, "ewma": track.ewma, "n": track.count}

    def observe_waterfalls(self, wfs) -> List[FleetFinding]:
        """Offline feed: per-replica TTFT/TPOT derived from
        reconstructed waterfalls, in request start order. The series key
        is the replica that served the (final) decode."""
        out: List[FleetFinding] = []
        for wf in sorted(wfs, key=lambda w: w.t0_ns):
            key = wf.replicas[-1] if wf.replicas else "unknown"
            if wf.ttft_s > 0.0:
                f = self.observe("ttft", key, wf.ttft_s)
                if f is not None:
                    out.append(f)
            tpot = wf.tpot_s
            if tpot is not None:
                f = self.observe("tpot", key, tpot)
                if f is not None:
                    out.append(f)
        return out


class GatewayProbe:
    """Online feed: instrument a live ``Gateway`` so every replica step
    lands in the detector while traffic runs.

    Wraps ``gw.pool.step_replica`` (restored by ``close()``): the wall
    time of one engine step is the per-replica TPOT proxy — a batched
    step emits one token per active request, so a replica whose steps
    suddenly take N x its own median (e.g. the failover survivor
    absorbing a dead replica's re-prefills) fires ``tpot_spike`` naming
    that replica in ``detail["key"]``.
    """

    def __init__(self, gw, detector: Optional[AnomalyDetector] = None):
        self.gw = gw
        self.detector = detector or AnomalyDetector()
        self._orig = gw.pool.step_replica
        gw.pool.step_replica = self._stepped

    def _stepped(self, rep):
        t0 = _time.perf_counter()
        out = self._orig(rep)
        self.detector.observe("tpot", rep.name,
                              _time.perf_counter() - t0)
        self.detector.observe("queue_depth", "gateway",
                              float(len(self.gw._queue)))
        return out

    @property
    def findings(self) -> List[FleetFinding]:
        return self.detector.findings

    def close(self):
        """Unhook; the detector (and its findings) stay readable."""
        self.gw.pool.step_replica = self._orig
