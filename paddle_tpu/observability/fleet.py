"""Fleet telemetry plane: rank-sharded spools + cross-rank aggregation.

Every observability surface below this module is per-process; this is
the layer that makes a multi-process world debuggable (MegaScale-style
per-worker monitoring + straggler attribution, PAPERS.md):

  * **Process identity** — ``rank`` / ``world_size`` / ``host``
    resolved once from the launcher's env (``PADDLE_TRAINER_ID`` /
    ``PADDLE_TRAINERS_NUM``); ``metrics.MetricsRegistry`` stamps the
    rank as a default label on every series when a distributed env is
    detected (and stays byte-identical when it is not).
  * **Per-rank spool** — when ``PADDLE_TELEMETRY_DIR`` is set, each
    process appends metrics snapshots, finished trace spans, and
    collective enter/exit events to its own ``rank<r>.jsonl`` shard
    (append + flush per line: a killed rank's shard is complete up to
    the moment of death). Serving/gateway step loops call
    ``autospool_tick`` so long-running engines snapshot periodically
    without user code.
  * **FleetAggregator** — merges shards into one fleet view (counters
    summed, histograms bucket-merged, gauges kept per-rank, spans
    unioned onto the wall clock) and reconstructs a per-collective
    cross-rank timeline with typed findings: ``straggler`` (arrival
    skew over threshold, with ``collective.skew_seconds{op}`` p50/p99
    gauges), ``desync`` (ranks entering different collectives — the
    runtime twin of the DF004 static lint), and ``missing_rank`` (a
    shard stops mid-collective). ``tools/telemetry_dump.py --fleet``
    is the CLI over all of it.

The hard-crash sibling is ``flight.py`` (binary ring journal); this
module is the high-volume, human-readable plane.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..utils.locks import TracedLock

__all__ = ["ProcessIdentity", "process_identity", "telemetry_dir",
           "TelemetrySpool", "get_spool", "spool_enabled", "reset_spool",
           "spool_metrics", "spool_event", "autospool_tick",
           "on_collective_enter", "on_collective_exit",
           "FleetFinding", "FleetAggregator",
           "DEFAULT_STRAGGLER_THRESHOLD_S"]

DEFAULT_STRAGGLER_THRESHOLD_S = 0.25


# -- process identity --------------------------------------------------------

@dataclass(frozen=True)
class ProcessIdentity:
    rank: int
    world_size: int
    host: str
    pid: int

    @property
    def distributed(self) -> bool:
        return self.world_size > 1


_IDENT: List[Optional[ProcessIdentity]] = [None]


def process_identity() -> ProcessIdentity:
    """This process's fleet identity, resolved once from the launcher
    env (rank 0 of a world of 1 when standalone)."""
    ident = _IDENT[0]
    if ident is None:
        ident = ProcessIdentity(
            rank=int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0),
            world_size=int(os.environ.get("PADDLE_TRAINERS_NUM", "1")
                           or 1),
            host=socket.gethostname(),
            pid=os.getpid())
        _IDENT[0] = ident
    return ident


def telemetry_dir() -> Optional[str]:
    return os.environ.get("PADDLE_TELEMETRY_DIR") or None


# -- per-rank spool ----------------------------------------------------------

class TelemetrySpool:
    """Append-only JSONL shard for ONE process (``rank<r>.jsonl``).

    Every line is flushed as written — a crashed rank's shard parses
    clean up to its last complete line (the reader tolerates one torn
    tail line). The first line is a ``meta`` record carrying the
    identity the aggregator joins on.
    """

    def __init__(self, dirpath: str,
                 identity: Optional[ProcessIdentity] = None):
        self.identity = identity or process_identity()
        os.makedirs(dirpath, exist_ok=True)
        self.path = os.path.join(
            dirpath, f"rank{self.identity.rank:05d}.jsonl")
        self._lock = TracedLock("TelemetrySpool._lock")
        self._f = open(self.path, "a")
        self.write({"kind": "meta", "rank": self.identity.rank,
                    "world_size": self.identity.world_size,
                    "host": self.identity.host, "pid": self.identity.pid,
                    "t": time.time()})

    def write(self, obj: dict) -> None:
        line = json.dumps(obj, separators=(",", ":"))
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()

    def metrics_snapshot(self) -> None:
        from .metrics import get_registry
        self.write({"kind": "metrics", "t": time.time(),
                    "series": get_registry().snapshot()})

    def span(self, span_dict: dict, wall_end: float) -> None:
        dur = span_dict.get("duration_s") or 0.0
        self.write({"kind": "span", "t": wall_end - dur,
                    "t_end": wall_end, **span_dict})

    def collective(self, phase: str, op: str, seq: int,
                   t: Optional[float] = None,
                   dur: Optional[float] = None) -> None:
        rec = {"kind": "collective", "phase": phase, "op": op,
               "seq": seq, "t": time.time() if t is None else t}
        if dur is not None:
            rec["dur"] = dur
        self.write(rec)

    def event(self, name: str, **fields) -> None:
        self.write({"kind": "event", "name": name, "t": time.time(),
                    **fields})

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


_UNPROBED = object()
_SPOOL = _UNPROBED   # _UNPROBED | None | TelemetrySpool
_SPOOL_LOCK = TracedLock("fleet._SPOOL_LOCK")


def get_spool() -> Optional[TelemetrySpool]:
    """This process's spool (lazily opened under PADDLE_TELEMETRY_DIR);
    None when spooling is disarmed."""
    global _SPOOL
    sp = _SPOOL
    if sp is not _UNPROBED:
        return sp
    with _SPOOL_LOCK:
        if _SPOOL is not _UNPROBED:
            return _SPOOL
        d = telemetry_dir()
        if not d:
            _SPOOL = None
            return None
        try:
            _SPOOL = TelemetrySpool(d)
        except OSError:
            _SPOOL = None
        return _SPOOL


def spool_enabled() -> bool:
    return get_spool() is not None


def reset_spool() -> None:
    """Close + drop the cached spool AND identity so the next use
    re-reads the env (tests)."""
    global _SPOOL
    with _SPOOL_LOCK:
        if _SPOOL not in (None, _UNPROBED):
            _SPOOL.close()
        _SPOOL = _UNPROBED
        _IDENT[0] = None
        _TICK[0] = 0.0


def spool_metrics() -> None:
    sp = get_spool()
    if sp is not None:
        sp.metrics_snapshot()


def spool_event(name: str, **fields) -> None:
    sp = get_spool()
    if sp is not None:
        sp.event(name, **fields)


_TICK = [0.0]


def autospool_tick(min_interval: Optional[float] = None) -> bool:
    """Rate-limited metrics snapshot for long-running loops (serving /
    gateway steps call this each tick). Returns True when a snapshot
    was written. Disarmed: one cached-global check."""
    if _SPOOL is None:
        return False
    sp = get_spool()
    if sp is None:
        return False
    iv = (min_interval if min_interval is not None else
          float(os.environ.get("PADDLE_TELEMETRY_INTERVAL", "1.0")))
    now = time.monotonic()
    if now - _TICK[0] < iv:
        return False
    _TICK[0] = now
    sp.metrics_snapshot()
    return True


# -- collective instrumentation (called from distributed.collective) ---------

_COLL_SEQ = [0]
_COLL_LOCK = TracedLock("fleet._COLL_LOCK")


def on_collective_enter(op: str) -> Optional[Tuple[int, float]]:
    """Record this rank ENTERING a collective (spool + flight ring).
    Returns the (seq, t_enter) token ``on_collective_exit`` needs, or
    None when both channels are disarmed. Runs BEFORE the chaos fault
    point so a kill_rank mid-collective leaves the tell-tale
    enter-without-exit in the victim's shard and ring."""
    sp = get_spool()
    from .flight import get_flight
    fl = get_flight()
    if sp is None and fl is None:
        return None
    with _COLL_LOCK:
        _COLL_SEQ[0] += 1
        seq = _COLL_SEQ[0]
    t = time.time()
    if sp is not None:
        sp.collective("enter", op, seq, t)
    if fl is not None:
        fl.record("collective_enter", wall_t=t, op=op, seq=seq)
    return seq, t


def on_collective_exit(token: Optional[Tuple[int, float]],
                       op: str) -> None:
    if token is None:
        return
    seq, t0 = token
    t = time.time()
    sp = get_spool()
    if sp is not None:
        sp.collective("exit", op, seq, t, dur=t - t0)
    from .flight import get_flight
    fl = get_flight()
    if fl is not None:
        fl.record("collective_exit", wall_t=t, op=op, seq=seq)


# -- aggregation -------------------------------------------------------------

@dataclass
class FleetFinding:
    """One typed cross-rank diagnosis from the collective timeline."""
    kind: str                       # straggler | desync | missing_rank
    op: str
    seq: int
    rank: Optional[int] = None      # the implicated rank
    skew_s: Optional[float] = None
    detail: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        out = {"kind": self.kind, "op": self.op, "seq": self.seq,
               "rank": self.rank, "detail": dict(self.detail)}
        if self.skew_s is not None:
            out["skew_s"] = self.skew_s
        return out

    def __str__(self):
        bits = [f"{self.kind}: op={self.op} seq={self.seq}"]
        if self.rank is not None:
            bits.append(f"rank={self.rank}")
        if self.skew_s is not None:
            bits.append(f"skew={self.skew_s:.3f}s")
        return " ".join(bits)


def _quantile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _bucket_quantile(bounds: List[float], counts: List[int], q: float,
                     mx: Optional[float]) -> Optional[float]:
    """Quantile estimate from merged cumulative buckets (upper-bound
    convention; the +Inf tail resolves to the merged max)."""
    total = sum(counts)
    if total == 0:
        return None
    target = q * total
    cum = 0
    for bound, c in zip(bounds, counts):
        cum += c
        if cum >= target:
            return bound
    return mx if mx is not None else bounds[-1]


class _RankShard:
    """One parsed rank<r>.jsonl file."""

    def __init__(self, path: str):
        self.path = path
        self.meta: dict = {}
        self.snapshots: List[dict] = []      # metrics records, in order
        self.spans: List[dict] = []
        self.collectives: List[dict] = []
        self.events: List[dict] = []
        self.records: List[dict] = []        # everything, append order
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue   # torn tail line from a crashed writer
                self.records.append(obj)
                k = obj.get("kind")
                if k == "meta":
                    self.meta = obj
                elif k == "metrics":
                    self.snapshots.append(obj)
                elif k == "span":
                    self.spans.append(obj)
                elif k == "collective":
                    self.collectives.append(obj)
                elif k == "event":
                    self.events.append(obj)
        self.rank = int(self.meta.get("rank", -1))

    @property
    def latest_series(self) -> List[dict]:
        return self.snapshots[-1]["series"] if self.snapshots else []


class FleetAggregator:
    """Merge every rank shard under one telemetry dir into a fleet view."""

    def __init__(self, dirpath: str):
        import glob
        self.dir = dirpath
        self.shards: Dict[int, _RankShard] = {}
        for path in sorted(glob.glob(os.path.join(dirpath,
                                                  "rank*.jsonl"))):
            try:
                shard = _RankShard(path)
            except OSError:
                continue
            if shard.rank >= 0:
                self.shards[shard.rank] = shard

    def ranks(self) -> List[int]:
        return sorted(self.shards)

    def identities(self) -> Dict[int, dict]:
        return {r: s.meta for r, s in sorted(self.shards.items())}

    # -- metric merge --------------------------------------------------------
    def fleet_series(self) -> List[dict]:
        """One merged series list: counters summed across ranks,
        histograms bucket-merged, gauges kept per-rank (a point-in-time
        value has no meaningful cross-rank sum), plus fleet meta gauges
        and the ``collective.skew_seconds{op}`` p50/p99 skew gauges."""
        counters: Dict[Tuple, dict] = {}
        hists: Dict[Tuple, dict] = {}
        out: List[dict] = []
        for rank, shard in sorted(self.shards.items()):
            for s in shard.latest_series:
                labels = dict(s.get("labels") or {})
                labels.pop("rank", None)
                key = (s["name"],
                       tuple(sorted(labels.items())))
                if s["type"] == "counter":
                    ent = counters.get(key)
                    if ent is None:
                        counters[key] = ent = {
                            "name": s["name"], "type": "counter",
                            "labels": labels, "value": 0, "ranks": []}
                    ent["value"] += s.get("value", 0)
                    ent["ranks"].append(rank)
                elif s["type"] == "histogram":
                    ent = hists.get(key)
                    if ent is None or \
                            ent["buckets"] != list(s.get("buckets") or []):
                        if ent is not None:
                            # bucket bounds diverged across ranks: keep
                            # the first merge and emit this one per-rank
                            out.append({**s, "labels": {
                                **labels, "rank": str(rank)}})
                            continue
                        hists[key] = ent = {
                            "name": s["name"], "type": "histogram",
                            "labels": labels,
                            "buckets": list(s.get("buckets") or []),
                            "bucket_counts": [0] * len(
                                s.get("bucket_counts") or []),
                            "count": 0, "sum": 0.0,
                            "min": None, "max": None, "ranks": []}
                    bc = s.get("bucket_counts") or []
                    if len(ent["bucket_counts"]) < len(bc):
                        ent["bucket_counts"] += [0] * (
                            len(bc) - len(ent["bucket_counts"]))
                    for i, c in enumerate(bc):
                        ent["bucket_counts"][i] += c
                    ent["count"] += s.get("count", 0)
                    ent["sum"] += s.get("sum", 0.0)
                    for fld, pick in (("min", min), ("max", max)):
                        v = s.get(fld)
                        if v is not None:
                            ent[fld] = (v if ent[fld] is None
                                        else pick(ent[fld], v))
                    ent["ranks"].append(rank)
                else:   # gauges (and external natives): per-rank truth
                    out.append({**s, "labels": {**labels,
                                                "rank": str(rank)}})
        for ent in hists.values():
            bounds, bc = ent["buckets"], ent["bucket_counts"]
            ent["quantiles"] = {
                f"p{int(q * 100)}": _bucket_quantile(bounds, bc, q,
                                                     ent["max"])
                for q in (0.5, 0.95, 0.99)}
        out.extend(counters.values())
        out.extend(hists.values())
        out.append({"name": "fleet.ranks_reporting", "type": "gauge",
                    "labels": {}, "value": float(len(self.shards)),
                    "peak": float(len(self.shards))})
        for op, skews in sorted(self._skews_by_op().items()):
            srt = sorted(skews)
            for q, qn in ((0.5, "p50"), (0.99, "p99")):
                qv = _quantile(srt, q)
                if qv is None:
                    continue
                out.append({"name": "collective.skew_seconds",
                            "type": "gauge",
                            "labels": {"op": op, "quantile": qn},
                            "value": qv, "peak": max(srt)})
        return out

    # -- spans ---------------------------------------------------------------
    def spans(self) -> List[dict]:
        """Every rank's finished spans unioned onto the wall clock
        (sorted by start time, rank attached)."""
        out = []
        for rank, shard in sorted(self.shards.items()):
            for sp in shard.spans:
                out.append({**sp, "rank": rank})
        out.sort(key=lambda s: (s.get("t", 0.0), s.get("rank", 0)))
        return out

    # -- collective timeline + findings --------------------------------------
    def collective_timeline(self) -> List[dict]:
        """Per-collective cross-rank view, ordered by seq: which op each
        rank entered at that position, and when it entered/exited."""
        by_seq: Dict[int, dict] = {}
        for rank, shard in sorted(self.shards.items()):
            for c in shard.collectives:
                seq = c.get("seq")
                ent = by_seq.setdefault(seq, {
                    "seq": seq, "op_by_rank": {}, "enter": {},
                    "exit": {}})
                if c.get("phase") == "enter":
                    ent["op_by_rank"][rank] = c.get("op")
                    ent["enter"][rank] = c.get("t")
                else:
                    ent["exit"][rank] = c.get("t")
        return [by_seq[s] for s in sorted(by_seq)]

    def _skews_by_op(self) -> Dict[str, List[float]]:
        skews: Dict[str, List[float]] = {}
        for ent in self.collective_timeline():
            ops = set(ent["op_by_rank"].values())
            if len(ops) != 1 or len(ent["enter"]) < 2:
                continue
            ts = list(ent["enter"].values())
            skews.setdefault(ops.pop(), []).append(max(ts) - min(ts))
        return skews

    def findings(self, straggler_threshold_s: Optional[float] = None
                 ) -> List[FleetFinding]:
        """Typed cross-rank diagnoses from the merged timeline."""
        thresh = (straggler_threshold_s if straggler_threshold_s
                  is not None else float(os.environ.get(
                      "PADDLE_FLEET_SKEW_THRESHOLD",
                      str(DEFAULT_STRAGGLER_THRESHOLD_S))))
        out: List[FleetFinding] = []
        timeline = self.collective_timeline()
        for ent in timeline:
            ops = ent["op_by_rank"]
            distinct = set(ops.values())
            if len(distinct) > 1:
                # runtime twin of the DF004 static lint: the ranks'
                # programs diverged. Implicate the minority op's ranks.
                by_op: Dict[str, List[int]] = {}
                for r, op in ops.items():
                    by_op.setdefault(op, []).append(r)
                minority_op = min(by_op, key=lambda o: len(by_op[o]))
                out.append(FleetFinding(
                    kind="desync", op=minority_op, seq=ent["seq"],
                    rank=by_op[minority_op][0],
                    detail={"op_by_rank": {str(r): o for r, o
                                           in sorted(ops.items())}}))
                continue
            if len(ent["enter"]) >= 2 and distinct:
                ts = ent["enter"]
                skew = max(ts.values()) - min(ts.values())
                if skew >= thresh:
                    slowest = max(ts, key=lambda r: ts[r])
                    out.append(FleetFinding(
                        kind="straggler", op=next(iter(distinct)),
                        seq=ent["seq"], rank=slowest, skew_s=skew,
                        detail={"enter_t": {str(r): t for r, t
                                            in sorted(ts.items())}}))
        # missing-rank: a rank left a collective ENTER unmatched and then
        # went SILENT (its shard's last write trails the fleet's last
        # write by > silence threshold). The silence clause is what
        # separates the dead rank from survivors blocked in the same
        # collective: a watchdog-aborted survivor also ends on an open
        # enter, but it kept writing until moments before the fleet's
        # final record.
        silence_s = float(os.environ.get(
            "PADDLE_FLEET_SILENCE_THRESHOLD", "1.0"))

        def _last_t(shard: _RankShard) -> float:
            return max((r.get("t_end") or r.get("t") or 0.0
                        for r in shard.records), default=0.0)

        fleet_last_t = max((_last_t(s) for s in self.shards.values()),
                           default=0.0)
        for rank, shard in sorted(self.shards.items()):
            exits = {c.get("seq") for c in shard.collectives
                     if c.get("phase") == "exit"}
            open_enters = [c for c in shard.collectives
                           if c.get("phase") == "enter"
                           and c.get("seq") not in exits]
            if not open_enters:
                continue
            last_open = max(open_enters,
                            key=lambda c: c.get("seq") or 0)
            gap = fleet_last_t - _last_t(shard)
            if gap >= silence_s:
                out.append(FleetFinding(
                    kind="missing_rank", op=last_open.get("op"),
                    seq=last_open.get("seq"), rank=rank,
                    detail={"last_t": _last_t(shard),
                            "fleet_last_t": fleet_last_t,
                            "silent_for_s": gap}))
        return out

    def summary(self) -> dict:
        findings = self.findings()
        return {
            "dir": self.dir,
            "ranks": self.ranks(),
            "world_size": max((s.meta.get("world_size", 1)
                               for s in self.shards.values()),
                              default=0),
            "collectives": len(self.collective_timeline()),
            "spans": sum(len(s.spans) for s in self.shards.values()),
            "findings": [f.to_dict() for f in findings],
        }
