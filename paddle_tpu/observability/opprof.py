"""Op-level compiled-program observatory (ISSUE 16 tentpole).

``roofline_attr`` explains a step's MFU gap at *phase* granularity
(compute / memory / overhead / comm:axis) but every optimization the
gap is supposed to direct — Pallas decode attention, quantized KV,
remat tuning — is an *op-level* decision. This module closes that
resolution gap without any runtime sampling: it reads the numbers XLA
already computed at compile time.

Three layers, all deterministic on the CPU backend:

  * **Taxonomy** — ``canon_op`` / ``classify_op`` map any op name (an
    optimized-HLO opcode, a fused-computation member, or an xplane
    trace op) into one shared bucket scheme::

        matmul | attention | collective | elementwise | reduce |
        data-movement | other

    ``tools/analyze_xplane.py`` imports THIS module, so real-TPU xplane
    captures and CPU cost-model profiles report identical buckets.

  * **Capture** — ``maybe_capture(label, jitted, args)`` AOT-lowers an
    already-built ``jax.jit`` callable at its live argument tuple,
    reads ``lowered.compile().cost_analysis()`` (module totals) and the
    optimized HLO text (per-op/per-fusion FLOPs, bytes-accessed and
    output bytes; ``while`` bodies are expanded by their
    ``known_trip_count``), and files an :class:`OpProfile` under the
    label. ``jit.TrainStep`` (single-device and ``mesh_plan=``),
    ``hapi.Model.prepare(jit=True)`` and the serving batchers'
    compiled prefill/decode call the hook at their warm transitions —
    a zero-cost no-op until :func:`enable` (or ``PADDLE_OPPROF=1``).

  * **Attribution + artifacts** — :func:`publish_gap_attribution`
    splits each ``roofline.gap_attribution`` phase across op classes
    (classes tile each phase's fraction exactly);
    :func:`write_artifact` persists ``OPPROF_r*.json`` with
    per-executable fingerprints and recompile counts, and
    :func:`diff` names exactly which ops appeared / disappeared /
    changed cost between two artifacts — a recompile storm or a
    fusion regression becomes a named finding instead of silent
    step-time drift. ``tools/profile_report.py`` is the CLI;
    ``tools/bench_guard.py`` gates the ``opprof:`` lane.

Module-level imports are stdlib-only on purpose: tools load this file
standalone (``importlib`` from path) for the taxonomy and artifact
views without paying the ``paddle_tpu``/jax import. Anything that
needs jax or the metrics registry imports lazily inside the function.
"""
from __future__ import annotations

import glob
import hashlib
import json
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "OP_CLASSES", "canon_op", "classify_op",
    "enable", "disable", "enabled", "reset_captures",
    "maybe_capture", "profile_compiled", "profile_hlo_text",
    "OpProfile", "get_captures", "recompile_counts",
    "op_class_table", "top_op_classes",
    "attribute_gap", "publish_gap_attribution",
    "write_artifact", "load_artifact", "artifact_paths", "diff",
    "bench_summary",
]

# The shared bucket scheme. Order is significant: it is the tie-break
# and display order everywhere (reports, gauges, artifacts).
OP_CLASSES = ("matmul", "attention", "collective", "elementwise",
              "reduce", "data-movement", "quant", "other")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# ---------------------------------------------------------------------------
# Taxonomy
# ---------------------------------------------------------------------------

# HLO opcodes / xplane op names by class. Names are matched after
# canonicalization ('-' and '_' fold to '-', instance ids dropped).
_MATMUL = {"dot", "dot-general", "convolution", "conv", "gemm",
           "cublas-gemm", "einsum", "matmul"}
_COLLECTIVE = {"all-reduce", "all-gather", "reduce-scatter",
               "all-to-all", "collective-permute", "collective-broadcast",
               "all-reduce-start", "all-reduce-done", "all-gather-start",
               "all-gather-done", "collective-permute-start",
               "collective-permute-done", "psum", "ppermute", "pmax",
               "pmin", "send", "send-done", "recv", "recv-done",
               "partition-id", "replica-id"}
_REDUCE = {"reduce", "reduce-window", "argmax", "argmin", "sort",
           "reduce-sum", "reduce-max", "reduce-min", "reduce-and",
           "reduce-or", "reduce-precision", "cumsum", "cumprod",
           "select-and-scatter", "topk", "top-k"}
_DATA_MOVEMENT = {"copy", "copy-start", "copy-done", "transpose",
                  "reshape", "broadcast", "broadcast-in-dim",
                  "concatenate", "slice", "dynamic-slice",
                  "dynamic-update-slice", "gather", "scatter", "pad",
                  "convert", "convert-element-type", "bitcast",
                  "bitcast-convert", "reverse", "infeed", "outfeed",
                  "tuple", "get-tuple-element", "parameter", "constant",
                  "iota", "after-all", "domain", "optimization-barrier"}
_TRANSCENDENTAL = {"tanh", "exp", "expm1", "log", "log1p", "logistic",
                   "sqrt", "rsqrt", "cbrt", "power", "pow", "erf",
                   "erf-inv", "sin", "cos", "tan", "atan2", "sigmoid"}
_ELEMENTWISE = _TRANSCENDENTAL | {
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "abs", "negate", "sign", "floor", "ceil", "round",
    "round-nearest-afz", "round-nearest-even", "clamp", "select",
    "compare", "and", "or", "xor", "not", "is-finite", "remainder",
    "shift-left", "shift-right-logical", "shift-right-arithmetic",
    "rem", "real", "imag", "complex", "map", "rng", "rng-bit-generator",
    "rng-get-and-update-state", "clz", "popcnt", "stochastic-convert",
    # jax primitive spellings — fusion classification falls back to the
    # op_name scope tail, which uses these rather than the HLO opcodes
    "mul", "sub", "div", "max", "min", "neg", "pow", "integer-pow",
    "square", "erf", "erfc", "erf-inv", "logistic"}
_ATTENTION_HINTS = ("flash", "attention", "attn", "mha",
                    "scaled-dot-product", "softmax")
# serving-quantization scopes (decode_attention's cachekv_quant /
# cachekv_dequant, _ConvertedLinear's weight_dequant). Checked BEFORE
# the attention hints: the inline cache dequant lives inside the
# attention computation, and "how much am I paying to (de)quantize" is
# exactly the attribution the quant lane needs split out.
_QUANT_HINTS = ("cachekv-quant", "cachekv-dequant", "weight-dequant",
                "quantize", "dequant")


def canon_op(name: str, fold: bool = True) -> str:
    """Collapse op instances to a stable identity: ``fusion.123`` ->
    ``fusion``, trailing HLO ids dropped; ``fold=True`` additionally
    folds ``_`` to ``-`` (HLO opcode spelling) for set lookups.

    Shared with ``tools/analyze_xplane.py`` (which passes
    ``fold=False`` to keep its historical PROFILES_SUMMARY.json key
    spelling) so xplane trace names and HLO instruction names collapse
    by ONE rule."""
    name = re.sub(r"\.\d+$", "", name)
    name = re.sub(r"\d+$", "", name) or name
    name = name.strip()
    return name.replace("_", "-") if fold else name


def classify_op(name: str, path: str = "") -> str:
    """Map one op (HLO opcode, fused-op name, or xplane trace op) into
    the shared class scheme. ``path`` is optional context (an HLO
    ``metadata op_name`` scope or a fusion's member list) — a dot
    inside an attention scope classifies as ``attention``, which is
    the attribution we want (attention matmuls vs projection matmuls
    are different optimization targets)."""
    c = canon_op(name).lower()
    ctx = (path or "").lower().replace("_", "-")
    if any(h in ctx for h in _QUANT_HINTS) \
            or any(h in c for h in _QUANT_HINTS):
        return "quant"
    if any(h in ctx for h in _ATTENTION_HINTS) \
            or any(h in c for h in _ATTENTION_HINTS):
        return "attention"
    if c in _MATMUL or c.startswith(("dot", "conv", "gemm")):
        return "matmul"
    if c in _COLLECTIVE or c.startswith(("all-", "collective-",
                                         "reduce-scatter")):
        return "collective"
    if c in _REDUCE or c.startswith("reduce"):
        return "reduce"
    if c in _DATA_MOVEMENT or c.startswith(("copy", "transpose",
                                            "reshape", "broadcast",
                                            "slice", "dynamic-")):
        return "data-movement"
    if c in _ELEMENTWISE:
        return "elementwise"
    return "other"


# ---------------------------------------------------------------------------
# HLO text parsing: per-op FLOPs / bytes from the optimized module
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1,
    "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1, "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16, "token": 0, "opaque": 0,
}

_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.+)$")
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%([\w.\-]+)\s*\(")
_META_RE = re.compile(r'metadata=\{[^}]*?op_name="([^"]+)"')
_CALLS_RE = re.compile(r"calls=%([\w.\-]+)")
_BODY_RE = re.compile(r"body=%([\w.\-]+)")
_TO_APPLY_RE = re.compile(r"to_apply=%([\w.\-]+)")
_TRIP_RE = re.compile(r'known_trip_count[":{\s]+n[":\s]+"?(\d+)')
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_WINDOW_RE = re.compile(r"window=\{[^}]*?size=([0-9x]+)")
_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')
_OPCODE_RE = re.compile(r"([a-z][a-z0-9\-]*)\(")

_SKIP_OPS = {"parameter", "constant", "tuple", "get-tuple-element",
             "after-all", "bitcast", "domain"}


def _shape_bytes(dtype: str, dims: str) -> Tuple[int, int]:
    """(elements, bytes) of one ``dtype[d0,d1,...]`` shape literal."""
    n = 1
    for d in dims.split(","):
        d = d.strip()
        if d:
            n *= int(d)
    return n, n * _DTYPE_BYTES.get(dtype, 4)


def _split_result_operands(rest: str) -> Tuple[str, str, str, str]:
    """Split one instruction's RHS into (result_types, opcode,
    operand_segment, attrs). The operand segment is the top-level
    paren group right after the opcode (operand types can nest parens
    for tuple-typed operands)."""
    m = _OPCODE_RE.search(rest)
    if m is None:
        return rest, "", "", ""
    opcode = m.group(1)
    result = rest[:m.start()]
    i = m.end() - 1  # at the '('
    depth = 0
    j = i
    for j in range(i, len(rest)):
        if rest[j] == "(":
            depth += 1
        elif rest[j] == ")":
            depth -= 1
            if depth == 0:
                break
    return result, opcode, rest[i + 1:j], rest[j + 1:]


class _Instr:
    __slots__ = ("name", "opcode", "out_elems", "out_bytes",
                 "operand_bytes", "attrs", "operands", "path")

    def __init__(self, name, opcode, out_elems, out_bytes,
                 operand_bytes, attrs, operands, path):
        self.name = name
        self.opcode = opcode
        self.out_elems = out_elems
        self.out_bytes = out_bytes
        self.operand_bytes = operand_bytes
        self.attrs = attrs
        self.operands = operands
        self.path = path


def _parse_computations(text: str) -> Tuple[Dict[str, List[_Instr]],
                                            Optional[str]]:
    """All computations in an HLO module: name -> instruction list,
    plus the ENTRY computation's name."""
    comps: Dict[str, List[_Instr]] = {}
    entry = None
    current: Optional[List[_Instr]] = None
    for line in text.splitlines():
        stripped = line.strip()
        # A computation header is '%name (params...) -> type {' — the
        # param list can NEST parens (tuple-typed args), so detect by
        # shape (ends with '{', no '=' before the param list) rather
        # than by a paren-balanced regex.
        if stripped.endswith("{") and "=" not in stripped.split("(")[0]:
            cm = _COMP_RE.match(stripped)
            if cm:
                current = comps.setdefault(cm.group(1), [])
                if stripped.startswith("ENTRY"):
                    entry = cm.group(1)
                continue
        im = _INSTR_RE.match(line)
        if im is None or current is None:
            continue
        name, rest = im.group(1), im.group(2)
        result, opcode, operands, attrs = _split_result_operands(rest)
        if not opcode:
            continue
        out_elems = out_bytes = 0
        for dt, dims in _SHAPE_RE.findall(result):
            e, b = _shape_bytes(dt, dims)
            out_elems += e
            out_bytes += b
        operand_bytes = 0
        for dt, dims in _SHAPE_RE.findall(operands):
            operand_bytes += _shape_bytes(dt, dims)[1]
        meta = _META_RE.search(attrs)
        path = meta.group(1) if meta else ""
        current.append(_Instr(name, opcode, out_elems, out_bytes,
                              operand_bytes, attrs, operands, path))
    return comps, entry


def _dot_flops(ins: _Instr) -> float:
    """2 * prod(out) * K for a dot; K from the lhs contracting dims."""
    cm = _CONTRACT_RE.search(ins.attrs)
    shapes = _SHAPE_RE.findall(ins.operands)
    if cm is None or not shapes:
        return 2.0 * ins.out_elems
    lhs_dims = [int(d) for d in shapes[0][1].split(",") if d.strip()]
    k = 1
    for idx in cm.group(1).split(","):
        idx = idx.strip()
        if idx and int(idx) < len(lhs_dims):
            k *= lhs_dims[int(idx)]
    return 2.0 * ins.out_elems * max(k, 1)


def _conv_flops(ins: _Instr) -> float:
    """~2 * prod(out) * prod(window) * C_in (kernel = window x Cin x
    Cout; estimate Cin as kernel_elems / (window * Cout-from-output))."""
    wm = _WINDOW_RE.search(ins.attrs)
    window = 1
    if wm:
        for d in wm.group(1).split("x"):
            window *= int(d)
    shapes = _SHAPE_RE.findall(ins.operands)
    kernel_elems = 1
    if len(shapes) >= 2:
        kernel_elems = _shape_bytes(*shapes[1])[0]
    return 2.0 * ins.out_elems * max(kernel_elems // max(window, 1), 1) \
        * window / max(window, 1) * (window if window > 1 else 1)


def _instr_cost(ins: _Instr, comps: Dict[str, List[_Instr]],
                depth: int = 0) -> Tuple[float, float, float, str]:
    """(flops, bytes_accessed, transcendentals, op_class) of one
    instruction; fusion/while/call expand their called computations."""
    op = canon_op(ins.opcode)
    if op == "fusion" or op == "call":
        called = _CALLS_RE.search(ins.attrs) or _TO_APPLY_RE.search(
            ins.attrs)
        f = b = t = 0.0
        classes: Dict[str, float] = {}
        if called and called.group(1) in comps and depth < 8:
            for m in comps[called.group(1)]:
                if canon_op(m.opcode) in _SKIP_OPS:
                    continue
                mf, _mb, mt, mc = _instr_cost(m, comps, depth + 1)
                f += mf
                t += mt
                classes[mc] = classes.get(mc, 0.0) + (mf or m.out_elems)
        # a fusion's memory traffic is its boundary, not its members
        b = float(ins.operand_bytes + ins.out_bytes)
        # the op_name scope tail ('.../reduce_sum') names the producing
        # jaxpr primitive — better identity than the fusion's own name,
        # which XLA prefixes with the FIRST member's opcode (a
        # 'broadcast_multiply_fusion' is a multiply, not a broadcast)
        cls = "other"
        if ins.path:
            cls = classify_op(ins.path.split("/")[-1], ins.path)
        if cls == "other":
            cls = classify_op(ins.name, ins.path)
        if cls == "other" and classes:
            cls = max(classes.items(),
                      key=lambda kv: (kv[1], -OP_CLASSES.index(kv[0])))[0]
        return f, b, t, cls
    if op == "while":
        body = _BODY_RE.search(ins.attrs)
        trip = 1
        tm = _TRIP_RE.search(ins.attrs)
        if tm:
            trip = max(int(tm.group(1)), 1)
        f = b = t = 0.0
        if body and body.group(1) in comps and depth < 8:
            for m in comps[body.group(1)]:
                if canon_op(m.opcode) in _SKIP_OPS:
                    continue
                mf, mb, mt, _ = _instr_cost(m, comps, depth + 1)
                f += mf
                b += mb
                t += mt
        return f * trip, b * trip, t * trip, "other"
    if op == "conditional":
        return 0.0, float(ins.operand_bytes + ins.out_bytes), 0.0, "other"
    cls = classify_op(ins.opcode, ins.path)
    bytes_acc = float(ins.operand_bytes + ins.out_bytes)
    if op in ("dot", "dot-general"):
        return _dot_flops(ins), bytes_acc, 0.0, cls
    if op in ("convolution", "conv"):
        return _conv_flops(ins), bytes_acc, 0.0, cls
    if op == "custom-call":
        tgt = _TARGET_RE.search(ins.attrs)
        if tgt:
            cls = classify_op(tgt.group(1), ins.path)
        return 2.0 * ins.out_elems, bytes_acc, 0.0, cls
    if op in _TRANSCENDENTAL:
        return float(ins.out_elems), bytes_acc, float(ins.out_elems), cls
    if cls == "reduce":
        # a reduction reads its input once: elements ~ operand elems
        return float(max(ins.operand_bytes // 4, ins.out_elems)), \
            bytes_acc, 0.0, cls
    if cls in ("data-movement", "collective"):
        return 0.0, bytes_acc, 0.0, cls
    return float(ins.out_elems), bytes_acc, 0.0, cls


def _display_name(ins: _Instr) -> str:
    """Stable human identity for diffing: the metadata op_name tail
    (scope path without the jit(...) wrappers), else the canon HLO
    name. ``while``-body members keep their scope so a scan-body dot
    stays distinguishable from a top-level dot."""
    if ins.path:
        parts = [p for p in ins.path.split("/")
                 if p and not p.startswith("jit(")]
        if parts:
            return "/".join(parts[-3:])
    return canon_op(ins.name)


def profile_hlo_text(text: str, label: str = "",
                     xla_totals: Optional[dict] = None) -> "OpProfile":
    """Parse one optimized-HLO module into an :class:`OpProfile`.

    Deterministic: same text -> same profile (the fingerprint is the
    sha1 of the text). ``while`` bodies are expanded by their
    ``known_trip_count`` backend config (1 when absent)."""
    comps, entry = _parse_computations(text)
    rows: Dict[Tuple[str, str], dict] = {}

    def _emit(ins: _Instr, mult: float):
        op = canon_op(ins.opcode)
        if op in _SKIP_OPS:
            return
        if op == "while":
            body = _BODY_RE.search(ins.attrs)
            trip = 1
            tm = _TRIP_RE.search(ins.attrs)
            if tm:
                trip = max(int(tm.group(1)), 1)
            if body and body.group(1) in comps:
                for m in comps[body.group(1)]:
                    _emit(m, mult * trip)
                return
        f, b, t, cls = _instr_cost(ins, comps)
        key = (_display_name(ins), cls)
        row = rows.setdefault(key, {
            "op": key[0], "class": cls, "flops": 0.0, "bytes": 0.0,
            "out_bytes": 0.0, "transcendentals": 0.0, "count": 0})
        row["flops"] += f * mult
        row["bytes"] += b * mult
        row["out_bytes"] += float(ins.out_bytes) * mult
        row["transcendentals"] += t * mult
        row["count"] += int(mult) if mult >= 1 else 1

    for ins in comps.get(entry or "", []):
        _emit(ins, 1.0)
    ops = sorted(rows.values(),
                 key=lambda r: (-r["flops"], -r["bytes"], r["op"]))
    fingerprint = hashlib.sha1(text.encode()).hexdigest()[:16]
    return OpProfile(label=label, fingerprint=fingerprint, ops=ops,
                     xla_totals=dict(xla_totals or {}))


# ---------------------------------------------------------------------------
# OpProfile
# ---------------------------------------------------------------------------

def _peaks() -> Tuple[float, float]:
    """(peak_flops/s, peak_hbm bytes/s) for the cost-unit time model —
    ROOFLINE.json when present, else v5e-class constants. Only RATIOS
    of cost units matter (shares), so the absolute scale is free."""
    path = os.environ.get("PADDLE_ROOFLINE") or os.path.join(
        _REPO, "ROOFLINE.json")
    try:
        with open(path) as f:
            d = json.load(f)
        return (float(d.get("peak_flops") or 197e12),
                float(d.get("peak_hbm") or 819e9))
    except (OSError, ValueError):
        return 197e12, 819e9


class OpProfile:
    """Per-op cost profile of ONE compiled executable."""

    def __init__(self, label: str, fingerprint: str, ops: List[dict],
                 xla_totals: Optional[dict] = None):
        self.label = label
        self.fingerprint = fingerprint
        self.ops = ops
        self.xla_totals = dict(xla_totals or {})

    # -- derived views ------------------------------------------------------
    def cost_units(self) -> Dict[str, float]:
        """Roofline time-model cost per op row: max(flops/peak,
        bytes/bw) — the per-op analog of t_ideal. Keyed by op name."""
        pf, pb = _peaks()
        return {r["op"]: max(r["flops"] / pf, r["bytes"] / pb)
                for r in self.ops}

    def op_class_table(self) -> Dict[str, dict]:
        """Aggregate by class: flops, bytes, cost units + shares."""
        pf, pb = _peaks()
        table = {c: {"flops": 0.0, "bytes": 0.0, "cost": 0.0, "n_ops": 0}
                 for c in OP_CLASSES}
        for r in self.ops:
            t = table[r["class"]]
            t["flops"] += r["flops"]
            t["bytes"] += r["bytes"]
            t["cost"] += max(r["flops"] / pf, r["bytes"] / pb)
            t["n_ops"] += 1
        total = sum(t["cost"] for t in table.values()) or 1.0
        for t in table.values():
            t["cost_share"] = t["cost"] / total
        return table

    def top_ops(self, k: int = 10) -> List[dict]:
        cu = self.cost_units()
        return sorted(self.ops, key=lambda r: -cu[r["op"]])[:k]

    def totals(self) -> dict:
        return {
            "flops": sum(r["flops"] for r in self.ops),
            "bytes": sum(r["bytes"] for r in self.ops),
            "n_ops": sum(r["count"] for r in self.ops),
            "xla": self.xla_totals,
        }

    # -- (de)serialization --------------------------------------------------
    def to_dict(self) -> dict:
        return {"label": self.label, "fingerprint": self.fingerprint,
                "ops": self.ops, "xla_totals": self.xla_totals}

    @classmethod
    def from_dict(cls, d: dict) -> "OpProfile":
        return cls(label=d.get("label", ""),
                   fingerprint=d.get("fingerprint", ""),
                   ops=list(d.get("ops") or []),
                   xla_totals=d.get("xla_totals") or {})


def op_class_table(profile: OpProfile) -> Dict[str, dict]:
    return profile.op_class_table()


def top_op_classes(profile: OpProfile, k: int = 5) -> List[Tuple[str,
                                                                 float]]:
    """[(class, cost_share), ...] descending, zero-share classes
    dropped."""
    table = profile.op_class_table()
    pairs = [(c, round(t["cost_share"], 6)) for c, t in table.items()
             if t["cost_share"] > 0]
    return sorted(pairs, key=lambda kv: -kv[1])[:k]


# ---------------------------------------------------------------------------
# Capture registry (process-wide, like the metrics registry)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_ENABLED = [False]
_CAPTURES: Dict[str, List[OpProfile]] = {}
_CAPTURE_FAILURES = [0]


def enabled() -> bool:
    return _ENABLED[0] or os.environ.get("PADDLE_OPPROF", "") not in (
        "", "0")


def enable() -> None:
    _ENABLED[0] = True


def disable() -> None:
    _ENABLED[0] = False


def reset_captures() -> None:
    with _LOCK:
        _CAPTURES.clear()
        _CAPTURE_FAILURES[0] = 0


def get_captures() -> Dict[str, List[OpProfile]]:
    with _LOCK:
        return {k: list(v) for k, v in _CAPTURES.items()}


def recompile_counts() -> Dict[str, int]:
    """Executable builds per label. >1 for a label that should compile
    once is a recompile — the storm detector's raw number."""
    with _LOCK:
        return {k: len(v) for k, v in _CAPTURES.items()}


def profile_compiled(compiled, label: str = "") -> OpProfile:
    """Profile an AOT-compiled jax executable (``lowered.compile()``
    result): module totals from ``cost_analysis()``, per-op rows from
    the optimized HLO text."""
    totals: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)) and ca:
            ca = ca[0]
        if isinstance(ca, dict):
            totals = {k: float(v) for k, v in ca.items()
                      if k in ("flops", "bytes accessed",
                               "transcendentals")}
    except Exception:  # backend without cost analysis: text-only
        totals = {}
    text = compiled.as_text()
    return profile_hlo_text(text, label=label, xla_totals=totals)


def maybe_capture(label: str, jitted, args: tuple,
                  kwargs: Optional[dict] = None) -> Optional[OpProfile]:
    """Capture hook the compiled paths call at their warm transition.

    No-op (and free) unless :func:`enabled`. AOT lowering only traces
    avals — donated live buffers are untouched and nothing executes;
    on TPU the persistent compile cache absorbs the AOT compile.
    Must never take down the caller: any failure increments
    ``opprof.capture_failures`` and returns None."""
    if not enabled():
        return None
    try:
        compiled = jitted.lower(*args, **(kwargs or {})).compile()
        prof = profile_compiled(compiled, label=label)
        with _LOCK:
            _CAPTURES.setdefault(label, []).append(prof)
        try:
            from paddle_tpu.observability.metrics import get_registry
            get_registry().counter(
                "opprof.captures_total",
                "compiled-executable cost profiles captured, by label",
                labelnames=("label",)).labels(label=label).inc()
        except Exception:
            pass
        return prof
    except Exception:
        _CAPTURE_FAILURES[0] += 1
        try:
            from paddle_tpu.observability.metrics import get_registry
            get_registry().counter(
                "opprof.capture_failures",
                "opprof capture attempts that raised (hook is "
                "best-effort by contract)").inc()
        except Exception:
            pass
        return None


def _latest_profile(prefer: str = "train") -> Optional[OpProfile]:
    """Newest capture, preferring labels containing ``prefer``."""
    with _LOCK:
        if not _CAPTURES:
            return None
        for lbl, profs in _CAPTURES.items():
            if prefer in lbl and profs:
                return profs[-1]
        for profs in _CAPTURES.values():
            if profs:
                return profs[-1]
    return None


# ---------------------------------------------------------------------------
# Gap attribution: phase fractions -> per-op-class gauges
# ---------------------------------------------------------------------------

def _tile_exactly(total: float, weights: Dict[str, float]
                  ) -> Dict[str, float]:
    """Split ``total`` over OP_CLASSES proportional to ``weights`` so
    the parts sum to ``total`` EXACTLY (fp residual folded into the
    largest part) — the tiling contract the tests assert."""
    out = {c: 0.0 for c in OP_CLASSES}
    wsum = sum(w for w in weights.values() if w > 0)
    if total <= 0:
        return out
    if wsum <= 0:
        out["other"] = total
        return out
    for c in OP_CLASSES:
        out[c] = total * max(weights.get(c, 0.0), 0.0) / wsum
    largest = max(out, key=lambda c: out[c])
    out[largest] += total - sum(out.values())
    return out


def attribute_gap(attr: dict, profile: OpProfile
                  ) -> Dict[str, Dict[str, float]]:
    """Split each roofline phase fraction across op classes.

    ``attr`` is :func:`roofline_attr.observe_train_step`'s return
    (``compute_frac`` / ``memory_frac`` / ``overhead_frac`` +
    optional ``comm_fracs``). Weighting per phase:

      * compute  — class FLOPs share (MXU time is flops-proportional);
      * memory   — class bytes-accessed share (exposed HBM);
      * overhead — class cost-unit share (dispatch/host cost tracks
        how many op-seconds each class puts on the timeline);
      * comm:axis — entirely ``collective``.

    Classes tile each phase exactly: for every phase,
    ``sum(split[phase].values()) == attr[phase_frac]``."""
    table = profile.op_class_table()
    flops_w = {c: t["flops"] for c, t in table.items()}
    bytes_w = {c: t["bytes"] for c, t in table.items()}
    cost_w = {c: t["cost"] for c, t in table.items()}
    split = {
        "compute": _tile_exactly(float(attr.get("compute_frac", 0.0)),
                                 flops_w),
        "memory": _tile_exactly(float(attr.get("memory_frac", 0.0)),
                                bytes_w),
        "overhead": _tile_exactly(float(attr.get("overhead_frac", 0.0)),
                                  cost_w),
    }
    for axis, frac in (attr.get("comm_fracs") or {}).items():
        part = {c: 0.0 for c in OP_CLASSES}
        part["collective"] = float(frac)
        split[f"comm:{axis}"] = part
    return split


def publish_gap_attribution(attr: dict,
                            profile: Optional[OpProfile] = None
                            ) -> Optional[Dict[str, Dict[str, float]]]:
    """Publish ``roofline.gap_attribution_opclass{phase,op_class}``
    from the newest train-step capture (or an explicit profile).
    Returns the split, or None when no profile is available — callers
    (roofline_attr) treat that as a silent no-op."""
    if profile is None:
        profile = _latest_profile(prefer="train")
    if profile is None:
        return None
    split = attribute_gap(attr, profile)
    try:
        from paddle_tpu.observability.metrics import get_registry
        g = get_registry().gauge(
            "roofline.gap_attribution_opclass",
            "per-phase step-time fractions split by op class (classes "
            "tile each roofline.gap_attribution phase exactly)",
            labelnames=("phase", "op_class"))
        for phase, parts in split.items():
            for cls in OP_CLASSES:
                g.labels(phase=phase, op_class=cls).set(parts[cls])
    except Exception:
        pass
    return split


# ---------------------------------------------------------------------------
# Artifacts: OPPROF_r*.json + diff
# ---------------------------------------------------------------------------

def artifact_paths(dirpath: Optional[str] = None) -> List[str]:
    d = dirpath or _REPO
    rx = re.compile(r"OPPROF_r(\d+)\.json$")
    paths = [p for p in glob.glob(os.path.join(d, "OPPROF_r*.json"))
             if rx.search(os.path.basename(p))]
    return sorted(paths, key=lambda p: int(
        rx.search(os.path.basename(p)).group(1)))


def load_artifact(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return None
    # driver dry-run wrappers ({n, cmd, rc, tail}) are not artifacts
    if not isinstance(d, dict) or "captures" not in d:
        return None
    return d


def write_artifact(dirpath: Optional[str] = None, tpu: bool = False,
                   extra: Optional[dict] = None,
                   gap_attribution: Optional[dict] = None,
                   path: Optional[str] = None) -> Optional[str]:
    """Persist the capture registry as the next ``OPPROF_rNN.json``.

    The artifact is self-contained: latest profile per label (full op
    table), per-label recompile counts and fingerprint history, the
    headline top-op-class share the bench_guard ``opprof:`` lane
    gates, and the newest per-op-class gap split when one was
    published. Returns the path, or None when nothing was captured."""
    caps = get_captures()
    if not caps:
        return None
    d = dirpath or _REPO
    if path is None:
        existing = artifact_paths(d)
        rx = re.compile(r"OPPROF_r(\d+)\.json$")
        nxt = (int(rx.search(os.path.basename(existing[-1])).group(1))
               + 1) if existing else 0
        path = os.path.join(d, f"OPPROF_r{nxt:02d}.json")
    profiles = {lbl: profs[-1] for lbl, profs in caps.items() if profs}
    headline_prof = (_latest_profile(prefer="train")
                     or next(iter(profiles.values())))
    top = top_op_classes(headline_prof, k=len(OP_CLASSES))
    doc = {
        "kind": "opprof",
        "tpu": bool(tpu),
        "captures": {lbl: p.to_dict() for lbl, p in profiles.items()},
        "recompiles": recompile_counts(),
        "fingerprints": {lbl: [p.fingerprint for p in profs]
                         for lbl, profs in caps.items()},
        "capture_failures": _CAPTURE_FAILURES[0],
        "headline": {
            "label": headline_prof.label,
            "fingerprint": headline_prof.fingerprint,
            "top_class": top[0][0] if top else "other",
            "top_share": top[0][1] if top else 0.0,
            "top_op_classes": top,
            "n_recompiles": max(
                sum(recompile_counts().values())
                - len(recompile_counts()), 0),
        },
    }
    if gap_attribution:
        doc["gap_attribution"] = gap_attribution
    if extra:
        doc.update(extra)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return path


def diff(old: dict, new: dict, share_tol: float = 0.02) -> dict:
    """Name exactly which ops appeared / disappeared / changed cost
    between two OPPROF artifacts (or two ``{label: profile_dict}``
    capture maps). ``changed`` lists ops whose cost share moved more
    than ``share_tol`` absolute. Also reports per-label fingerprint
    flips and recompile-count growth — the named form of a recompile
    storm."""
    old_caps = old.get("captures", old) or {}
    new_caps = new.get("captures", new) or {}

    def _shares(caps) -> Dict[str, float]:
        pf, pb = _peaks()
        cost: Dict[str, float] = {}
        for lbl, pd in caps.items():
            for r in (pd.get("ops") or []):
                key = f"{lbl}:{r['op']}"
                cost[key] = cost.get(key, 0.0) + max(
                    r.get("flops", 0.0) / pf, r.get("bytes", 0.0) / pb)
        total = sum(cost.values()) or 1.0
        return {k: v / total for k, v in cost.items()}

    so, sn = _shares(old_caps), _shares(new_caps)
    appeared = sorted(k for k in sn if k not in so)
    disappeared = sorted(k for k in so if k not in sn)
    changed = []
    for k in sorted(set(so) & set(sn)):
        delta = sn[k] - so[k]
        if abs(delta) > share_tol:
            changed.append({"op": k, "old_share": round(so[k], 6),
                            "new_share": round(sn[k], 6),
                            "delta": round(delta, 6)})
    changed.sort(key=lambda c: -abs(c["delta"]))
    fp_changed = []
    for lbl in set(old_caps) & set(new_caps):
        of = (old_caps[lbl] or {}).get("fingerprint")
        nf = (new_caps[lbl] or {}).get("fingerprint")
        if of and nf and of != nf:
            fp_changed.append(lbl)
    ro = old.get("recompiles") or {}
    rn = new.get("recompiles") or {}
    storms = {lbl: {"old": ro.get(lbl, 0), "new": rn[lbl]}
              for lbl in rn if rn[lbl] > ro.get(lbl, rn[lbl])}
    return {"appeared": appeared, "disappeared": disappeared,
            "changed": changed, "fingerprint_changed": sorted(fp_changed),
            "recompile_growth": storms}


def bench_summary(top_k: int = 5) -> Optional[dict]:
    """The compact block bench.py embeds into ``BENCH_r*.json`` detail:
    top-k op-class cost table + executable fingerprint + recompiles."""
    prof = _latest_profile(prefer="train")
    if prof is None:
        return None
    return {
        "label": prof.label,
        "fingerprint": prof.fingerprint,
        "top_op_classes": top_op_classes(prof, k=top_k),
        "recompiles": recompile_counts(),
        "n_ops": sum(r["count"] for r in prof.ops),
    }
