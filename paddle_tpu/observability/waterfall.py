"""Per-request latency waterfalls reconstructed from trace spans.

The tracing layer (``trace_context``) records what happened —
queue/admit/prefill/decode/stream spans minted at ``Gateway.submit`` and
closed as the request moves gateway -> router -> replica -> batcher.
This module turns those flat span records into *attribution*: one
``Waterfall`` per trace with

- ordered segments on a common timebase (offsets relative to the root),
- per-phase totals (queue wait, admission, prefill adjusted for prefix
  hits, per-token decode, speculation-verify share, requeue overhead
  after a failover),
- the **critical path**: at every instant the deepest open span owns the
  wall clock, so each span is credited only its *self time* (time not
  covered by a deeper child) and the ordered owner sequence is the
  critical path through the stack,
- an explicit ``incomplete`` flag instead of an exception when the
  record set is torn (a crashed rank's fleet spool missing exit
  records, a trace whose root never closed): partial waterfalls still
  render, they just say so.

Span sources are interchangeable: live ``TraceRecorder`` spans, a JSONL
export, or fleet-spool records from ``FleetAggregator.spans()`` (same
dict shape plus ``kind``/``t``/``rank`` bookkeeping). The goodput
ledger (``observability.ledger``) and ``tools/trace_analyze.py`` both
consume the waterfalls built here.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional

__all__ = [
    "Segment", "Waterfall", "build_waterfalls", "waterfalls_from_recorder",
    "waterfalls_from_fleet", "critical_path_summary", "render_waterfall",
]

ROOT_SPAN = "gateway.request"


def _coerce(span) -> Optional[dict]:
    """Normalize one span record: a live ``TraceSpan``, a recorder/JSONL
    dict, or a fleet-spool record (span dict + ``kind``/``t``/``rank``).
    Returns None for records that are not spans at all; open spans
    (``end_ns`` None) come back with ``_open`` set so the builder can
    flag the trace incomplete instead of raising."""
    if hasattr(span, "to_dict"):
        d = span.to_dict()
    elif isinstance(span, dict):
        d = span
    else:
        return None
    if d.get("kind") not in (None, "span"):
        return None
    tid = d.get("trace_id")
    sid = d.get("span_id")
    if tid is None or sid is None:
        return None
    start = d.get("start_ns")
    end = d.get("end_ns")
    if start is None:
        # wall-clock-only record (foreign exporter): fall back to t/t_end
        t = d.get("t")
        if t is None:
            return None
        start = int(float(t) * 1e9)
        te = d.get("t_end")
        end = None if te is None else int(float(te) * 1e9)
    return {
        "trace_id": tid,
        "span_id": sid,
        "parent_id": d.get("parent_id"),
        "name": d.get("name", "?"),
        "start_ns": int(start),
        "end_ns": None if end is None else int(end),
        "tags": dict(d.get("tags") or {}),
        "rank": d.get("rank"),
        "_open": end is None,
    }


@dataclass
class Segment:
    """One span placed on the waterfall: offsets are seconds relative to
    the trace start; ``self_s`` is the span's critical-path credit (time
    no deeper span was open)."""
    name: str
    span_id: str
    parent_id: Optional[str]
    start_s: float
    duration_s: float
    self_s: float
    depth: int
    tags: Dict[str, object] = field(default_factory=dict)
    rank: Optional[int] = None

    def to_dict(self) -> dict:
        d = {"name": self.name, "span_id": self.span_id,
             "parent_id": self.parent_id,
             "start_s": round(self.start_s, 9),
             "duration_s": round(self.duration_s, 9),
             "self_s": round(self.self_s, 9), "depth": self.depth,
             "tags": self.tags}
        if self.rank is not None:
            d["rank"] = self.rank
        return d


@dataclass
class Waterfall:
    """One request's reconstructed timeline + phase attribution."""
    trace_id: str
    gid: Optional[int]
    tenant: Optional[str]
    rung: Optional[int]
    t0_ns: int
    total_s: float
    segments: List[Segment]
    critical_path: List[dict]          # ordered {name, span_id, self_s}
    phases: Dict[str, dict]            # name -> {seconds, self_seconds, count}
    tokens: Optional[int]
    requeues: int
    incomplete: bool
    replicas: List[str]

    # -- derived attribution ---------------------------------------------------
    def phase_seconds(self, name: str, self_time: bool = False) -> float:
        ph = self.phases.get(name)
        if ph is None:
            return 0.0
        return ph["self_seconds"] if self_time else ph["seconds"]

    @property
    def queue_wait_s(self) -> float:
        return self.phase_seconds("queue")

    @property
    def prefill_s(self) -> float:
        return self.phase_seconds("prefill")

    @property
    def ttft_s(self) -> float:
        """Submit to end of (last) prefill — the trace-side TTFT proxy."""
        ends = [s.start_s + s.duration_s for s in self.segments
                if s.name == "prefill"]
        return max(ends) if ends else 0.0

    @property
    def tpot_s(self) -> Optional[float]:
        """Per-token decode latency: decode span time over tokens."""
        dec = self.phase_seconds("decode")
        if not dec or not self.tokens:
            return None
        return dec / max(int(self.tokens), 1)

    def _prefill_tag_sum(self, key: str) -> int:
        return sum(int(s.tags.get(key) or 0) for s in self.segments
                   if s.name == "prefill")

    @property
    def prompt_tokens(self) -> int:
        return self._prefill_tag_sum("prompt_tokens")

    @property
    def prefix_hit_tokens(self) -> int:
        """Prompt rows served from the radix prefix cache (the prefill
        spans' ``prefix_hit`` tags): the rows prefill did NOT compute."""
        return self._prefill_tag_sum("prefix_hit")

    @property
    def spec_rejected_tokens(self) -> int:
        segs = [s for s in self.segments if s.name == "decode"]
        prop = sum(int(s.tags.get("spec_proposed") or 0) for s in segs)
        match = sum(int(s.tags.get("spec_matched") or 0) for s in segs)
        return max(prop - match, 0)

    @property
    def requeue_overhead_s(self) -> float:
        """Extra time a failover cost this request: work interrupted on
        the dead replica, the re-queue wait, and the survivor's
        duplicated re-prefill (``requeue_recompute=1``)."""
        out = 0.0
        for s in self.segments:
            t = s.tags
            if t.get("interrupted") or t.get("requeue_recompute") \
                    or (s.name == "queue" and t.get("requeued")):
                out += s.duration_s
        return out

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id, "gid": self.gid,
            "tenant": self.tenant, "rung": self.rung,
            "total_s": round(self.total_s, 9),
            "incomplete": self.incomplete,
            "tokens": self.tokens, "requeues": self.requeues,
            "replicas": self.replicas,
            "ttft_s": round(self.ttft_s, 9),
            "tpot_s": (None if self.tpot_s is None
                       else round(self.tpot_s, 9)),
            "queue_wait_s": round(self.queue_wait_s, 9),
            "prefill_s": round(self.prefill_s, 9),
            "prompt_tokens": self.prompt_tokens,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "spec_rejected_tokens": self.spec_rejected_tokens,
            "requeue_overhead_s": round(self.requeue_overhead_s, 9),
            "phases": self.phases,
            "critical_path": self.critical_path,
            "segments": [s.to_dict() for s in self.segments],
        }


def _depths(spans: List[dict]) -> Dict[str, int]:
    by_id = {s["span_id"]: s for s in spans}
    memo: Dict[str, int] = {}

    def depth(sid: str) -> int:
        if sid in memo:
            return memo[sid]
        memo[sid] = 0  # cycle guard (malformed input)
        parent = by_id[sid]["parent_id"]
        d = 0 if parent is None else (
            depth(parent) + 1 if parent in by_id else 1)
        memo[sid] = d
        return d

    return {sid: depth(sid) for sid in by_id}


def _build_one(trace_id: str, raw: List[dict]) -> Waterfall:
    incomplete = any(s["_open"] for s in raw)
    spans = [s for s in raw if not s["_open"]]
    ids = {s["span_id"] for s in spans}
    roots = [s for s in spans if s["parent_id"] is None]
    root = min(roots, key=lambda s: s["start_ns"]) if roots else None
    if root is None or any(s["parent_id"] is not None
                           and s["parent_id"] not in ids for s in spans):
        # torn record set: a crashed process never spooled the exit
        # records, so parents (often the root itself) are missing
        incomplete = True
    if not spans:
        return Waterfall(trace_id, None, None, None, 0, 0.0, [], [], {},
                         None, 0, True, [])
    spans.sort(key=lambda s: (s["start_ns"], s["end_ns"]))
    t0 = root["start_ns"] if root is not None \
        else min(s["start_ns"] for s in spans)
    t1 = root["end_ns"] if root is not None \
        else max(s["end_ns"] for s in spans)
    depth = _depths(spans)

    # critical path: sweep the elementary intervals between span
    # boundaries; each interval is owned by the deepest (then latest-
    # started) span covering it — that owner's self time
    bounds = sorted({b for s in spans for b in (s["start_ns"], s["end_ns"])})
    self_ns: Dict[str, int] = {s["span_id"]: 0 for s in spans}
    owners: List[tuple] = []          # (a_ns, b_ns, span)
    for a, b in zip(bounds, bounds[1:]):
        if b <= a:
            continue
        active = [s for s in spans
                  if s["start_ns"] <= a and s["end_ns"] >= b]
        if not active:
            continue
        own = max(active, key=lambda s: (depth[s["span_id"]],
                                         s["start_ns"]))
        self_ns[own["span_id"]] += b - a
        if owners and owners[-1][2] is own and owners[-1][1] == a:
            owners[-1] = (owners[-1][0], b, own)
        else:
            owners.append((a, b, own))
    critical_path = [{"name": s["name"], "span_id": s["span_id"],
                      "self_s": (b - a) / 1e9}
                     for a, b, s in owners]

    segments = [Segment(
        name=s["name"], span_id=s["span_id"], parent_id=s["parent_id"],
        start_s=(s["start_ns"] - t0) / 1e9,
        duration_s=(s["end_ns"] - s["start_ns"]) / 1e9,
        self_s=self_ns[s["span_id"]] / 1e9,
        depth=depth[s["span_id"]], tags=s["tags"], rank=s["rank"],
    ) for s in spans]

    phases: Dict[str, dict] = {}
    for seg in segments:
        ph = phases.setdefault(seg.name, {"seconds": 0.0,
                                          "self_seconds": 0.0, "count": 0})
        ph["seconds"] += seg.duration_s
        ph["self_seconds"] += seg.self_s
        ph["count"] += 1

    rtags = root["tags"] if root is not None else {}
    tokens = rtags.get("tokens")
    if tokens is None:
        toks = [s.tags.get("tokens") for s in segments
                if s.name == "decode" and s.tags.get("tokens") is not None]
        tokens = toks[-1] if toks else None
    replicas: List[str] = []
    for s in segments:
        r = s.tags.get("replica")
        if r is not None and r not in replicas:
            replicas.append(r)
    return Waterfall(
        trace_id=trace_id,
        gid=rtags.get("gid"),
        tenant=rtags.get("tenant"),
        rung=rtags.get("rung"),
        t0_ns=t0,
        total_s=max(t1 - t0, 0) / 1e9,
        segments=segments,
        critical_path=critical_path,
        phases=phases,
        tokens=None if tokens is None else int(tokens),
        requeues=sum(1 for s in segments if s.name == "requeue"),
        incomplete=incomplete,
        replicas=replicas,
    )


def build_waterfalls(spans: Iterable) -> List[Waterfall]:
    """Group span records by trace and reconstruct one ``Waterfall`` per
    trace, ordered by trace start. Never raises on torn input — partial
    traces come back with ``incomplete=True``."""
    groups: Dict[str, List[dict]] = {}
    for s in spans:
        d = _coerce(s)
        if d is None:
            continue
        groups.setdefault(d["trace_id"], []).append(d)
    out = [_build_one(tid, ss) for tid, ss in groups.items()]
    out.sort(key=lambda w: w.t0_ns)
    return out


def waterfalls_from_recorder(recorder=None) -> List[Waterfall]:
    """Waterfalls for every trace in the (default) live recorder."""
    if recorder is None:
        from .trace_context import get_recorder
        recorder = get_recorder()
    return build_waterfalls(recorder.spans())


def waterfalls_from_fleet(dirpath: str) -> List[Waterfall]:
    """Waterfalls from a fleet telemetry spool directory — the offline
    path: rank shards are parsed tolerant of torn tails, so a crashed
    rank degrades to partial (``incomplete``) waterfalls."""
    from .fleet import FleetAggregator
    return build_waterfalls(FleetAggregator(dirpath).spans())


def critical_path_summary(waterfalls: Iterable[Waterfall]) -> Dict[str, float]:
    """Aggregate critical-path self-seconds by span name across many
    requests — 'where does the fleet's request wall clock actually go'."""
    out: Dict[str, float] = {}
    for wf in waterfalls:
        for hop in wf.critical_path:
            out[hop["name"]] = out.get(hop["name"], 0.0) + hop["self_s"]
    return dict(sorted(out.items(), key=lambda kv: -kv[1]))


def _fmt_s(x: float) -> str:
    return f"{x * 1e3:.2f}ms" if x < 1.0 else f"{x:.3f}s"


def render_waterfall(wf: Waterfall, width: int = 48) -> str:
    """Fixed-width text waterfall (one bar per segment, offsets to
    scale) + the critical path — shared by trace_analyze and
    telemetry_dump --waterfall."""
    head = (f"trace {wf.trace_id} gid={wf.gid} tenant={wf.tenant} "
            f"total={_fmt_s(wf.total_s)} tokens={wf.tokens}")
    if wf.requeues:
        head += f" requeues={wf.requeues}"
    if wf.incomplete:
        head += " [INCOMPLETE]"
    lines = [head]
    span = max(wf.total_s, 1e-9)
    for seg in wf.segments:
        a = int(round(seg.start_s / span * width))
        n = max(1, int(round(seg.duration_s / span * width)))
        a = min(a, width - 1)
        n = min(n, width - a)
        bar = " " * a + "#" * n + " " * (width - a - n)
        label = "  " * min(seg.depth, 4) + seg.name
        extra = ""
        for k in ("replica", "prefix_hit", "interrupted",
                  "requeue_recompute", "preempted"):
            if seg.tags.get(k) is not None:
                extra += f" {k}={seg.tags[k]}"
        lines.append(f"  {label:<18s}|{bar}| "
                     f"{_fmt_s(seg.duration_s)}"
                     f" (self {_fmt_s(seg.self_s)}){extra}")
    path = " -> ".join(f"{h['name']}:{_fmt_s(h['self_s'])}"
                       for h in wf.critical_path)
    lines.append(f"  critical path: {path or '(none)'}")
    return "\n".join(lines)
