"""Fleet-wide goodput ledger: chip-seconds attributed by cause.

Aggregates per-request ``Waterfall``s (``observability.waterfall``) into
the accounting ROADMAP items 4/5 need: every span's *self time* is
charged to ``{tenant, rung, phase}`` (self time, so nested spans never
double-bill an interval), chip phases (admit/prefill/decode — time an
engine actually held the accelerator) are separated from wait phases
(queue/stream/gateway overhead), and chip time that produced nothing a
user received is itemized into explicit **waste categories**:

- ``bucket_pad``              — prefill rows burned on bucket-ladder
                                padding (``padded_to`` vs real
                                ``prompt_tokens``, prefix hits excluded
                                from the computed width),
- ``requeue_recompute``       — the survivor's duplicated prompt
                                re-prefill after a token-exact failover
                                (prefill spans tagged
                                ``requeue_recompute=1``),
- ``evicted_prefix_recompute``— re-prefill of prompt+tokens after a
                                preemption evicted the request's KV
                                (``evict_recompute=1``; split by
                                repayment path in
                                ``evicted_prefix_split`` — a
                                ``host_promoted`` resume restored its
                                prefix from the KV host tier and only
                                re-prefilled the residual suffix),
- ``speculation_rejected``    — the share of decode spent scoring
                                draft tokens the verifier rejected
                                (``spec_proposed``/``spec_matched``
                                tags on the decode span),
- ``recompile``               — XLA compile seconds pulled from the
                                ``compile.elapsed`` series (opt-in via
                                ``add_recompile_from_registry``; compile
                                time is process-wide, not per-trace),
- ``dequant``                 — main-thread blob dequantize seconds paid
                                installing quantized tier promotions,
                                pulled from the ``quant.dequant_seconds``
                                series (opt-in via
                                ``add_dequant_from_registry``; the
                                capacity tier_quant buys is NOT free and
                                this is its price, visible).

``goodput_frac`` = 1 - waste/chip. Invariant the drills assert: total
charged seconds equal the summed span self time — nothing the traces
saw goes missing and nothing is counted twice. ``publish()`` mirrors
the ledger into the metrics registry as ``ledger.goodput_frac``,
``ledger.waste_seconds{category}`` and
``ledger.chip_seconds{tenant,rung,phase}`` series so exporters,
bench_gateway artifacts and the future remediator all read one source.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

from .waterfall import Waterfall

__all__ = ["WASTE_CATEGORIES", "CHIP_PHASES", "GoodputLedger",
           "ledger_from_waterfalls"]

WASTE_CATEGORIES = ("bucket_pad", "requeue_recompute",
                    "evicted_prefix_recompute", "speculation_rejected",
                    "recompile", "dequant")
# span names that hold an engine (chip time); everything else is wait
# or gateway overhead — charged, reported, but outside goodput_frac
CHIP_PHASES = frozenset({"admit", "prefill", "decode"})


class GoodputLedger:
    """Mutable accumulator: ``add()`` waterfalls, read ``summary()``."""

    def __init__(self):
        self.requests = 0
        self.incomplete = 0
        self.charged_s = 0.0          # every span self-second, any phase
        self.chip_s = 0.0             # admit/prefill/decode self-seconds
        self.waste: Dict[str, float] = {c: 0.0 for c in WASTE_CATEGORIES}
        # evicted_prefix_recompute, split by HOW the eviction was repaid:
        # "host_promoted" resumes pulled the prefix back from the KV host
        # tier (waste = only the residual suffix re-prefill), "recomputed"
        # ones re-prefilled the whole thing. Sums to the category total.
        self.evicted_split: Dict[str, float] = {"host_promoted": 0.0,
                                                "recomputed": 0.0}
        self.by_key: Dict[Tuple[str, str, str], float] = {}

    # -- charging --------------------------------------------------------------
    def add(self, wf: Waterfall) -> "GoodputLedger":
        self.requests += 1
        if wf.incomplete:
            self.incomplete += 1
        tenant = wf.tenant if wf.tenant is not None else "unknown"
        rung = "-" if wf.rung is None else str(wf.rung)
        for seg in wf.segments:
            key = (tenant, rung, seg.name)
            self.by_key[key] = self.by_key.get(key, 0.0) + seg.self_s
            self.charged_s += seg.self_s
            if seg.name not in CHIP_PHASES:
                continue
            self.chip_s += seg.self_s
            cat, w = self._waste_of(seg)
            if cat is not None and w > 0.0:
                w = min(w, seg.self_s)
                self.waste[cat] += w
                if cat == "evicted_prefix_recompute":
                    path = ("host_promoted"
                            if seg.tags.get("host_promoted") else
                            "recomputed")
                    self.evicted_split[path] += w
        return self

    def add_all(self, wfs: Iterable[Waterfall]) -> "GoodputLedger":
        for wf in wfs:
            self.add(wf)
        return self

    @staticmethod
    def _waste_of(seg) -> Tuple[Optional[str], float]:
        t = seg.tags
        if seg.name == "prefill":
            if t.get("requeue_recompute"):
                return "requeue_recompute", seg.self_s
            if t.get("evict_recompute"):
                return "evicted_prefix_recompute", seg.self_s
            padded = t.get("padded_to")
            prompt = t.get("prompt_tokens")
            if padded and prompt and padded > prompt:
                # pad rows over the rows prefill actually computed
                # (prefix-cache hits were never computed at all)
                computed = max(int(padded) - int(t.get("prefix_hit") or 0),
                               1)
                frac = (int(padded) - int(prompt)) / computed
                return "bucket_pad", seg.self_s * frac
        elif seg.name == "decode":
            proposed = int(t.get("spec_proposed") or 0)
            if proposed > 0:
                rejected = max(proposed - int(t.get("spec_matched") or 0),
                               0)
                rounds = int(t.get("spec_rounds") or 0)
                # the verify pass scores proposed+rounds positions per
                # covered token; the rejected share bought nothing
                frac = rejected / max(proposed + rounds, 1)
                return "speculation_rejected", seg.self_s * frac
        return None, 0.0

    def add_recompile_from_registry(self, registry=None) -> float:
        """Charge XLA compile wall time (the ``compile.elapsed``
        histogram the jit layer feeds) as ``recompile`` waste. Returns
        the seconds added. Compile time is process-wide — it joins both
        the chip total and the waste column so goodput_frac stays a
        fraction of all accounted chip time."""
        if registry is None:
            from .metrics import get_registry
            registry = get_registry()
        secs = 0.0
        for series in registry.snapshot():
            if series.get("name") == "compile.elapsed":
                secs += float(series.get("sum") or 0.0)
        if secs > 0.0:
            self.waste["recompile"] += secs
            self.chip_s += secs
            self.charged_s += secs
        return secs

    def add_dequant_from_registry(self, registry=None) -> float:
        """Charge tier-blob dequantize time (the ``quant.dequant_seconds``
        histogram the batcher's promotion install feeds) as ``dequant``
        waste. Same shape as :meth:`add_recompile_from_registry`: the
        time is process-wide main-thread work outside any request span,
        so it joins both the chip total and the waste column."""
        if registry is None:
            from .metrics import get_registry
            registry = get_registry()
        secs = 0.0
        for series in registry.snapshot():
            if series.get("name") == "quant.dequant_seconds":
                secs += float(series.get("sum") or 0.0)
        if secs > 0.0:
            self.waste["dequant"] += secs
            self.chip_s += secs
            self.charged_s += secs
        return secs

    # -- reading ---------------------------------------------------------------
    @property
    def waste_s(self) -> float:
        return sum(self.waste.values())

    @property
    def goodput_frac(self) -> float:
        if self.chip_s <= 0.0:
            return 1.0
        return max(0.0, 1.0 - self.waste_s / self.chip_s)

    def summary(self) -> dict:
        by_phase: Dict[str, float] = {}
        by_tenant: Dict[str, float] = {}
        by_rung: Dict[str, float] = {}
        for (tenant, rung, phase), s in self.by_key.items():
            by_phase[phase] = by_phase.get(phase, 0.0) + s
            by_tenant[tenant] = by_tenant.get(tenant, 0.0) + s
            by_rung[rung] = by_rung.get(rung, 0.0) + s
        return {
            "requests": self.requests,
            "incomplete": self.incomplete,
            "charged_seconds": self.charged_s,
            "chip_seconds": self.chip_s,
            "goodput_seconds": max(self.chip_s - self.waste_s, 0.0),
            "goodput_frac": self.goodput_frac,
            "waste_seconds": dict(self.waste),
            "evicted_prefix_split": dict(self.evicted_split),
            "by_phase": dict(sorted(by_phase.items(),
                                    key=lambda kv: -kv[1])),
            "by_tenant": dict(sorted(by_tenant.items(),
                                     key=lambda kv: -kv[1])),
            "by_rung": dict(sorted(by_rung.items(),
                                   key=lambda kv: -kv[1])),
            "attribution": [
                {"tenant": t, "rung": r, "phase": p,
                 "seconds": s}
                for (t, r, p), s in sorted(self.by_key.items(),
                                           key=lambda kv: -kv[1])],
        }

    def publish(self, registry=None) -> None:
        """Mirror the ledger into the metrics registry (gauges, so a
        re-publish after more traffic just moves the needle)."""
        if registry is None:
            from .metrics import get_registry
            registry = get_registry()
        registry.gauge(
            "ledger.goodput_frac",
            "fraction of accounted chip-seconds that were not waste",
        ).set(self.goodput_frac)
        waste_g = registry.gauge(
            "ledger.waste_seconds",
            "chip-seconds lost, by cause",
            labelnames=("category",))
        for cat, s in self.waste.items():
            waste_g.labels(category=cat).set(s)
        split_g = registry.gauge(
            "ledger.evicted_prefix_seconds",
            "evicted_prefix_recompute waste by repayment path "
            "(host_promoted vs recomputed)",
            labelnames=("path",))
        for path, s in self.evicted_split.items():
            split_g.labels(path=path).set(s)
        chip_g = registry.gauge(
            "ledger.chip_seconds",
            "span self-seconds charged by tenant/rung/phase",
            labelnames=("tenant", "rung", "phase"))
        for (tenant, rung, phase), s in self.by_key.items():
            chip_g.labels(tenant=tenant, rung=rung, phase=phase).set(s)


def ledger_from_waterfalls(wfs: Iterable[Waterfall],
                           recompile_from_registry: bool = False
                           ) -> GoodputLedger:
    led = GoodputLedger().add_all(wfs)
    if recompile_from_registry:
        led.add_recompile_from_registry()
    return led
