"""Unified runtime telemetry: metrics registry, trace spans, exporters.

The one pipe every subsystem reports through (reference analog:
platform/monitor.h STATS_INT + the host profiler, fused):

  * ``metrics`` — process-wide Counter / Gauge / Histogram registry with
    labeled series; counters ride the C++ stat tier when available.
  * ``tracing`` — nested, context-propagated spans that feed BOTH the
    profiler's chrome-trace recorder and span-duration histograms.
  * ``export`` — Prometheus text format + JSONL snapshots
    (``tools/telemetry_dump.py`` is the CLI over these).
  * ``fleet`` — rank-sharded telemetry spools under
    ``PADDLE_TELEMETRY_DIR`` + cross-rank aggregation with typed
    straggler/desync/missing-rank findings (``telemetry_dump --fleet``).
  * ``flight`` — crash-surviving per-rank binary ring journal, replayed
    by ``tools/blackbox.py postmortem``.
  * ``waterfall`` / ``ledger`` / ``anomaly`` — the attribution layer:
    per-request critical-path waterfalls reconstructed from recorded
    spans, the fleet goodput ledger (chip-seconds by tenant/rung/phase
    with typed waste categories), and streaming EWMA/MAD detectors over
    per-replica TTFT/TPOT/queue-depth emitting ``FleetFinding``s
    (``tools/trace_analyze.py`` is the CLI over all three).
  * ``opprof`` — compiled-program cost profiles: per-op/per-fusion
    FLOPs and bytes parsed from the optimized HLO of every warm
    executable (TrainStep, serving prefill/decode), a shared op-class
    taxonomy (also used by ``tools/analyze_xplane.py``), per-op-class
    MFU-gap attribution, and ``OPPROF_r*.json`` artifacts with a
    ``diff()`` that names recompiles and fusion regressions
    (``tools/profile_report.py`` is the CLI; the bench_guard
    ``opprof:`` lane is the gate).

Instrumented out of the box: serving batchers (queue depth, admissions,
preemptions, TTFT / per-token latency), the multi-replica serving
gateway (``gateway.*``: routing affinity hits, per-tenant sheds,
requeues off dead replicas, end-to-end TTFT/TPOT — dump with
``tools/telemetry_dump.py --prefix gateway.``), collectives
(bytes/count/latency per op), the hapi training loop (step time,
tokens/sec, MFU), the Pallas flash-attention autotune cache, and the
static-analysis passes (``analysis.findings{rule=...}`` — every DF/SH/MEM
diagnostic pass counts its findings by rule here).
"""
from __future__ import annotations

from . import (anomaly, export, fleet, flight, ledger, metrics, opprof,
               roofline_attr, slo, trace_context, tracing, waterfall)
from .opprof import OpProfile, classify_op
from .anomaly import AnomalyDetector, GatewayProbe
from .export import load_jsonl, render_prometheus, write_jsonl
from .fleet import (FleetAggregator, FleetFinding, ProcessIdentity,
                    TelemetrySpool, get_spool, process_identity)
from .ledger import GoodputLedger, ledger_from_waterfalls
from .flight import (FlightRecorder, build_postmortem, flight_record,
                     get_flight, read_ring)
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      get_registry)
from .slo import (SLO, Alert, BurnWindow, Resolved, SLOMonitor,
                  default_gateway_slos)
from .trace_context import (TraceContext, TraceRecorder, TraceSpan,
                            get_recorder, new_trace)
from .tracing import (Span, attach_context, capture_context, current_span,
                      span, span_path, traced)
from .waterfall import (Waterfall, build_waterfalls,
                        critical_path_summary, render_waterfall,
                        waterfalls_from_fleet, waterfalls_from_recorder)

__all__ = [
    "metrics", "tracing", "export", "trace_context", "roofline_attr",
    "slo", "fleet", "flight", "waterfall", "ledger", "anomaly",
    "opprof", "OpProfile", "classify_op",
    "Waterfall", "build_waterfalls", "waterfalls_from_recorder",
    "waterfalls_from_fleet", "critical_path_summary", "render_waterfall",
    "GoodputLedger", "ledger_from_waterfalls",
    "AnomalyDetector", "GatewayProbe",
    "FleetAggregator", "FleetFinding", "ProcessIdentity",
    "TelemetrySpool", "get_spool", "process_identity",
    "FlightRecorder", "build_postmortem", "flight_record", "get_flight",
    "read_ring",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "Span", "span", "current_span", "span_path", "capture_context",
    "attach_context", "traced",
    "TraceContext", "TraceSpan", "TraceRecorder", "get_recorder",
    "new_trace",
    "SLO", "Alert", "BurnWindow", "Resolved", "SLOMonitor",
    "default_gateway_slos",
    "render_prometheus", "write_jsonl", "load_jsonl",
]
