"""Process-wide metrics registry: Counter / Gauge / Histogram with labels.

Reference: paddle/fluid/platform/monitor.h (STATS_INT — named int64 gauges
registered once and sampled framework-wide) generalized into the three
Prometheus instrument kinds every serving/training stack ends up needing:

  * Counter   — monotone int64, backed by the C++ stat registry
                (csrc/native.cc, shared with the data-loader and tracer
                tiers) when available, with the same pure-python fallback
                ``utils/monitor.py`` uses;
  * Gauge     — settable float with a tracked peak (PEAK_VALUE analog);
                integer-valued gauges may opt into the native tier so
                cross-thread writers (the C++ dataloader) share the cell;
  * Histogram — fixed buckets + a bounded reservoir for streaming
                p50/p95/p99 estimates (pure python; observations are
                floats the int registry can't carry).

Labeled series: ``registry.counter(name, labelnames=("engine",))`` returns
a family; ``family.labels(engine="dense")`` returns the per-series child.
All instruments are thread-safe. ``registry.snapshot()`` renders every
series (plus, optionally, native-registry names owned by other tiers) as
plain dicts that ``observability.export`` serializes.
"""
from __future__ import annotations

import bisect
import os
import random
import threading
import zlib
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..core import native as _native

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "DEFAULT_BUCKETS", "DEFAULT_QUANTILES"]

# latency-shaped default buckets (seconds); +Inf is implicit
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
DEFAULT_QUANTILES: Tuple[float, ...] = (0.5, 0.95, 0.99)
_RESERVOIR_CAP = 512

# -- native-tier plumbing ----------------------------------------------------
# One tier per process (the monitor.py divergence fix lives on this choice):
# probe once, then every native-backed cell uses the chosen tier forever. A
# native call failing AFTER the probe is logged once and the delta dropped —
# never silently split across tiers.
_TIER_LOCK = threading.Lock()
_TIER: Optional[str] = None          # "native" | "py" once probed
_TIER_FAIL_LOGGED = False


def _tier() -> str:
    global _TIER
    if _TIER is None:
        with _TIER_LOCK:
            if _TIER is None:
                try:
                    _native.stat_update("__observability_probe__", 0)
                    _TIER = "native"
                except Exception:
                    _TIER = "py"
    return _TIER


def _log_tier_failure_once(exc: Exception) -> None:
    global _TIER_FAIL_LOGGED
    with _TIER_LOCK:
        if _TIER_FAIL_LOGGED:
            return
        _TIER_FAIL_LOGGED = True
    import logging
    logging.getLogger(__name__).warning(
        "native stat tier failed mid-run (%s: %s); the registry sticks "
        "with the native tier — this delta (and any later failing ones) "
        "is dropped rather than silently diverging into a python shadow "
        "store", type(exc).__name__, exc)


class _NativeCell:
    """Int cell in the cross-thread stat registry (current + peak)."""

    __slots__ = ("key",)

    def __init__(self, key: str):
        self.key = key

    def add(self, delta: int) -> int:
        try:
            return int(_native.stat_update(self.key, int(delta)))
        except Exception as exc:  # noqa: BLE001 — see _log_tier_failure_once
            _log_tier_failure_once(exc)
            return self.get_int()

    def get_int(self) -> int:
        try:
            v = _native.stat_get(self.key)
        except Exception:
            return 0
        return int(v[0] if isinstance(v, tuple) else v)

    def peak_int(self) -> int:
        try:
            v = _native.stat_get(self.key)
        except Exception:
            return 0
        return int(v[1] if isinstance(v, tuple) else v)

    def reset(self) -> None:
        try:
            _native.stat_reset(self.key)
        except Exception:
            pass


class _PyCell:
    """Float cell (current + peak) guarded by its own lock."""

    __slots__ = ("_lock", "cur", "pk")

    def __init__(self):
        self._lock = threading.Lock()
        self.cur = 0.0
        self.pk = 0.0

    def add(self, delta: float) -> float:
        with self._lock:
            self.cur += delta
            self.pk = max(self.pk, self.cur)
            return self.cur

    def set(self, value: float) -> None:
        with self._lock:
            self.cur = value
            self.pk = max(self.pk, value)

    def get(self) -> float:
        return self.cur

    def peak(self) -> float:
        return self.pk

    def reset(self) -> None:
        with self._lock:
            self.cur = 0.0
            self.pk = 0.0


def _series_key(name: str, labels: Dict[str, str]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


# -- instruments -------------------------------------------------------------

class Counter:
    """Monotone int64 counter; rides the native stat tier when available."""

    kind = "counter"

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.labels = dict(labels or {})
        key = _series_key(name, self.labels)
        self._cell = _NativeCell(key) if _tier() == "native" else _PyCell()

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a Gauge")
        self._cell.add(int(n))

    @property
    def value(self) -> int:
        if isinstance(self._cell, _NativeCell):
            return self._cell.get_int()
        return int(self._cell.get())

    def _reset(self) -> None:
        self._cell.reset()

    def _series(self) -> dict:
        return {"name": self.name, "type": self.kind,
                "labels": self.labels, "value": self.value}


class Gauge:
    """Settable float gauge with a tracked peak (PEAK_VALUE analog).

    ``native=True`` keeps the cell in the cross-thread int registry (the
    monitor.py shim uses this so C++-tier writers share it); the default
    python cell carries floats (MFU, rates).
    """

    kind = "gauge"

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None,
                 native: bool = False):
        self.name = name
        self.labels = dict(labels or {})
        key = _series_key(name, self.labels)
        self._cell = (_NativeCell(key)
                      if native and _tier() == "native" else _PyCell())

    def add(self, delta: float) -> float:
        if isinstance(self._cell, _NativeCell):
            return self._cell.add(int(delta))
        return self._cell.add(delta)

    def set(self, value: float) -> None:
        if isinstance(self._cell, _NativeCell):
            self._cell.add(int(value) - self._cell.get_int())
        else:
            self._cell.set(value)

    @property
    def value(self) -> float:
        if isinstance(self._cell, _NativeCell):
            return float(self._cell.get_int())
        return self._cell.get()

    @property
    def peak(self) -> float:
        if isinstance(self._cell, _NativeCell):
            return float(self._cell.peak_int())
        return self._cell.peak()

    def _reset(self) -> None:
        self._cell.reset()

    def _series(self) -> dict:
        return {"name": self.name, "type": self.kind,
                "labels": self.labels, "value": self.value,
                "peak": self.peak}


class Histogram:
    """Fixed-bucket histogram + bounded-reservoir streaming quantiles.

    Buckets are upper bounds (ascending; +Inf implicit). The reservoir
    (uniform, seeded from the series name so test runs are reproducible)
    keeps a bounded sample of observations for p50/p95/p99 estimates —
    exact below ``_RESERVOIR_CAP`` observations, an unbiased estimate
    above it.
    """

    kind = "histogram"

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None,
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.labels = dict(labels or {})
        bs = tuple(sorted(buckets if buckets is not None else
                          DEFAULT_BUCKETS))
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bs
        self._lock = threading.Lock()
        self._counts = [0] * (len(bs) + 1)       # last = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None
        self._reservoir: List[float] = []
        # crc32, not hash(): str hashing is salted per process and the
        # reservoir must behave identically run to run
        self._rng = random.Random(zlib.crc32(
            _series_key(name, self.labels).encode()))

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._counts[bisect.bisect_left(self.buckets, v)] += 1
            self._sum += v
            self._count += 1
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)
            if len(self._reservoir) < _RESERVOIR_CAP:
                self._reservoir.append(v)
            else:
                j = self._rng.randrange(self._count)
                if j < _RESERVOIR_CAP:
                    self._reservoir[j] = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> Optional[float]:
        """Streaming quantile estimate from the reservoir (None if empty).

        q is validated into [0, 1]; the extremes return the EXACT
        observed min/max (tracked over every observation — past the
        reservoir cap the sampled extremes may have been evicted)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile q must be in [0, 1], got {q}")
        with self._lock:
            if not self._reservoir:
                return None
            if q == 0.0:
                return self._min
            if q == 1.0:
                return self._max
            s = sorted(self._reservoir)
        if len(s) == 1:
            return s[0]
        idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
        return s[idx]

    def bucket_counts(self) -> List[int]:
        """Raw per-bucket counts (len(buckets)+1; the tail is +Inf)."""
        with self._lock:
            return list(self._counts)

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0
            self._min = self._max = None
            self._reservoir = []

    def _series(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, ssum = self._count, self._sum
            mn, mx = self._min, self._max
        return {"name": self.name, "type": self.kind,
                "labels": self.labels,
                "buckets": list(self.buckets),
                "bucket_counts": counts,
                "count": total, "sum": ssum,
                "min": mn, "max": mx,
                "quantiles": {f"p{int(q * 100)}": self.quantile(q)
                              for q in DEFAULT_QUANTILES}}


class _Family:
    """Labeled metric family: one (name, labelnames) entry in the registry
    fanning out to per-label-value child instruments."""

    def __init__(self, name: str, kind: str, help: str,
                 labelnames: Tuple[str, ...], make_child):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = labelnames
        self._make_child = make_child
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def labels(self, **kw):
        if set(kw) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kw)} != declared "
                f"{sorted(self.labelnames)}")
        key = tuple(str(kw[k]) for k in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make_child(
                        dict(zip(self.labelnames, key)))
                    self._children[key] = child
        return child

    def children(self):
        with self._lock:
            return list(self._children.values())


class MetricsRegistry:
    """Name -> instrument/family map; the process-wide telemetry root."""

    def __init__(self):
        self._lock = threading.RLock()
        self._entries: Dict[str, object] = {}
        self._kinds: Dict[str, str] = {}
        self._default_labels: Optional[Dict[str, str]] = None

    # -- registry-wide default labels ---------------------------------------
    # Stamped onto every snapshot series (explicit series labels win).
    # Unset, they resolve from the distributed env at snapshot time:
    # {"rank": <PADDLE_TRAINER_ID>} in a multi-process world, {} when
    # world_size == 1 — single-process output stays byte-identical.
    def set_default_labels(self, **labels: str) -> None:
        with self._lock:
            self._default_labels = {k: str(v) for k, v in labels.items()}

    def clear_default_labels(self) -> None:
        """Back to env-resolved defaults (tests)."""
        with self._lock:
            self._default_labels = None

    def default_labels(self) -> Dict[str, str]:
        with self._lock:
            if self._default_labels is not None:
                return dict(self._default_labels)
        try:
            world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1)
        except ValueError:
            world = 1
        if world > 1:
            return {"rank": os.environ.get("PADDLE_TRAINER_ID", "0")
                    or "0"}
        return {}

    # -- registration (idempotent; kind mismatch is an error) ---------------
    def _get_or_make(self, name: str, kind: str, help: str,
                     labelnames: Sequence[str], make_child):
        labelnames = tuple(labelnames or ())
        with self._lock:
            if name in self._entries:
                if self._kinds[name] != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{self._kinds[name]}, not {kind}")
                entry = self._entries[name]
                if labelnames and not isinstance(entry, _Family):
                    raise ValueError(f"metric {name!r} is unlabeled")
                if isinstance(entry, _Family) \
                        and entry.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} labelnames {entry.labelnames} "
                        f"!= {labelnames}")
                return entry
            if labelnames:
                entry = _Family(name, kind, help, labelnames, make_child)
            else:
                entry = make_child({})
                entry.help = help
            self._entries[name] = entry
            self._kinds[name] = kind
            return entry

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()):
        return self._get_or_make(
            name, "counter", help, labelnames,
            lambda labels: Counter(name, labels))

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = (), native: bool = False):
        return self._get_or_make(
            name, "gauge", help, labelnames,
            lambda labels: Gauge(name, labels, native=native))

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None):
        return self._get_or_make(
            name, "histogram", help, labelnames,
            lambda labels: Histogram(name, labels, buckets=buckets))

    def get(self, name: str):
        return self._entries.get(name)

    # -- snapshot -----------------------------------------------------------
    def _instruments(self) -> Iterable:
        with self._lock:
            entries = list(self._entries.values())
        for entry in entries:
            if isinstance(entry, _Family):
                for child in entry.children():
                    yield child
            else:
                yield entry

    def snapshot(self, include_native: bool = True) -> List[dict]:
        """Every live series as plain dicts (export.py serializes these).

        include_native also surfaces native-registry names written by
        OTHER tiers (the C++ dataloader, monitor gauges predating the
        registry) as gauge series, so one snapshot covers the process.
        """
        out = [inst._series() for inst in self._instruments()]
        if include_native:
            owned = {_series_key(s["name"], s["labels"]) for s in out}
            try:
                native_all = _native.stat_all() or {}
            except Exception:
                native_all = {}
            for key, v in sorted(native_all.items()):
                if key in owned or key.startswith("__observability"):
                    continue
                cur, pk = (v if isinstance(v, tuple) else (v, v))
                out.append({"name": key, "type": "gauge", "labels": {},
                            "value": float(cur), "peak": float(pk),
                            "external": True})
        defaults = self.default_labels()
        if defaults:
            for s in out:
                s["labels"] = {**defaults, **(s["labels"] or {})}
        return out

    def reset(self) -> None:
        """Zero every registered series (tests); external tiers untouched."""
        for inst in self._instruments():
            inst._reset()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem reports through."""
    return _REGISTRY
