"""Trace spans: named, nested, context-propagated timing scopes.

One recorder feeds two sinks: every span wraps a ``profiler.RecordEvent``
(so an active Profiler window sees it in chrome-trace exports and the
summary table, host-tracer tier included) AND observes its duration into
the ``span_duration_seconds`` histogram of the metrics registry (so p50/
p95/p99 per span name are queryable with no profiler attached).

Nesting is tracked per thread; ``capture_context()`` / ``attach_context``
carry the active span path across thread (or executor) boundaries, the
way the reference's host tracer threads its correlation ids.
"""
from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

from ..profiler import RecordEvent
from .metrics import get_registry

__all__ = ["Span", "span", "current_span", "span_path",
           "capture_context", "attach_context", "traced"]

_TLS = threading.local()


def _stack():
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = []
        _TLS.stack = st
    return st


def _span_hist():
    return get_registry().histogram(
        "span_duration_seconds",
        "trace span wall time by span name", labelnames=("span",))


class Span:
    """One named timing scope (context manager, re-usable via span())."""

    __slots__ = ("name", "path", "start_ns", "end_ns", "_record")

    def __init__(self, name: str):
        self.name = name
        self.path = name          # finalized at __enter__ from the stack
        self.start_ns = None
        self.end_ns = None
        self._record = None

    @property
    def duration_s(self) -> Optional[float]:
        if self.start_ns is None or self.end_ns is None:
            return None
        return (self.end_ns - self.start_ns) / 1e9

    def __enter__(self) -> "Span":
        st = _stack()
        self.path = (st[-1].path + "/" + self.name) if st else self.name
        st.append(self)
        self._record = RecordEvent(self.name)
        self._record.begin()
        self.start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> bool:
        self.end_ns = time.perf_counter_ns()
        if self._record is not None:
            self._record.end()
            self._record = None
        st = _stack()
        if self in st:       # tolerate mis-nested exits instead of corrupting
            while st and st[-1] is not self:
                st.pop()
            st.pop()
        _span_hist().labels(span=self.name).observe(self.duration_s)
        return False


def span(name: str) -> Span:
    """``with span("decode_step"): ...`` — the primary entry point."""
    return Span(name)


def current_span() -> Optional[Span]:
    st = _stack()
    return st[-1] if st else None


def span_path() -> str:
    """Slash-joined active span path of this thread ("" outside spans)."""
    st = _stack()
    return st[-1].path if st else ""


def capture_context() -> Tuple[str, ...]:
    """Token carrying this thread's active span names (for propagation)."""
    return tuple(s.name for s in _stack())


class attach_context:
    """Re-establish a captured span context in another thread::

        token = capture_context()        # producer thread
        ...
        with attach_context(token):      # worker thread
            with span("stage"): ...      # path includes the producer's spans

    The attached parents are name-only placeholders: they do not time or
    re-record the producer's spans, they only restore the nesting path.
    """

    def __init__(self, token: Tuple[str, ...]):
        self._token = tuple(token or ())
        self._placeholders = []

    def __enter__(self):
        st = _stack()
        for name in self._token:
            ph = Span(name)
            ph.path = (st[-1].path + "/" + name) if st else name
            st.append(ph)
            self._placeholders.append(ph)
        return self

    def __exit__(self, *exc):
        st = _stack()
        for ph in reversed(self._placeholders):
            if ph in st:
                while st and st[-1] is not ph:
                    st.pop()
                st.pop()
        self._placeholders = []
        return False


def traced(name: Optional[str] = None):
    """Decorator form: time every call of the function as a span."""
    import functools

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with Span(label):
                return fn(*args, **kwargs)
        return wrapper
    return deco
