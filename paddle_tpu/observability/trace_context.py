"""Request-scoped distributed tracing (Dapper-style propagated contexts).

``tracing.py`` times NESTED scopes inside one thread; this module follows
ONE REQUEST across subsystem boundaries: a ``TraceContext`` (trace_id +
span ids + baggage) is minted at ``Gateway.submit``, handed through the
dispatch queue, the router, the replica's batcher (which stores it on its
per-request ``Request`` record), and the ``StreamingSession`` — surviving
token-exact requeue off a dead replica, where the resumed request keeps
the ORIGINAL trace_id and every later span carries the ``requeued=1``
baggage tag. The result: a single request's TTFT decomposes into
queue / admit / prefill / decode / stream spans you can open in
``chrome://tracing``.

Spans are recorded with explicit begin/end timestamps (not context
managers) because serving spans open in one call and close several steps
later — e.g. ``decode`` opens at admission and closes when the request
finishes. ``TraceSpan.end`` is idempotent, so abort paths (replica
death, deadline expiry, preemption) can close whatever is open without
double-recording.

Propagation across process boundaries uses the W3C ``traceparent``
header shape (``00-<trace_id>-<span_id>-01``) plus a ``baggage``
``k=v`` list — ``TraceContext.traceparent()`` /
``TraceContext.from_traceparent`` round-trip it.

Set ``PADDLE_TRACE=0`` to disable minting entirely (hot-path cost drops
to one ``is None`` check per event).
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

__all__ = ["TraceContext", "TraceSpan", "TraceRecorder", "get_recorder",
           "new_trace", "enabled", "end_open_spans"]

_TRACE_IDS = itertools.count(1)
_SPAN_IDS = itertools.count(1)
_RECORDER_CAP = int(os.environ.get("PADDLE_TRACE_CAP", "8192"))


def enabled() -> bool:
    """Tracing on/off switch (env ``PADDLE_TRACE``, default on)."""
    return os.environ.get("PADDLE_TRACE", "1") != "0"


def _flight_record(kind: str, **fields) -> None:
    # one cached-global check when the flight ring is disarmed
    from .flight import flight_record
    flight_record(kind, **fields)


def _trace_metrics():
    from .metrics import get_registry
    reg = get_registry()
    return (reg.counter("trace.spans_total",
                        "request-trace spans recorded"),
            reg.counter("trace.spans_dropped",
                        "spans evicted from the bounded trace ring"),
            reg.histogram("trace.span_seconds",
                          "request-trace span wall time by span name",
                          labelnames=("span",)))


class TraceSpan:
    """One timed scope of one request's trace (explicit begin/end)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "start_ns", "end_ns", "tags")

    def __init__(self, trace_id: str, name: str,
                 parent_id: Optional[str] = None,
                 tags: Optional[Dict[str, object]] = None):
        self.trace_id = trace_id
        self.span_id = f"{next(_SPAN_IDS):08x}"
        self.parent_id = parent_id
        self.name = name
        self.start_ns = time.perf_counter_ns()
        self.end_ns: Optional[int] = None
        self.tags: Dict[str, object] = dict(tags or {})
        _flight_record("span_open", name=name, trace_id=trace_id,
                       span_id=self.span_id)

    @property
    def open(self) -> bool:
        return self.end_ns is None

    @property
    def duration_s(self) -> Optional[float]:
        if self.end_ns is None:
            return None
        return (self.end_ns - self.start_ns) / 1e9

    def end(self, **tags) -> "TraceSpan":
        """Close + record the span (idempotent: abort paths may race the
        normal close; the first end wins, later calls only merge tags)."""
        if self.end_ns is not None:
            self.tags.update(tags)
            return self
        self.end_ns = time.perf_counter_ns()
        self.tags.update(tags)
        _flight_record("span_close", name=self.name,
                       trace_id=self.trace_id, span_id=self.span_id)
        get_recorder().record(self)
        spans_c, _, span_h = _trace_metrics()
        spans_c.inc()
        span_h.labels(span=self.name).observe(self.duration_s)
        return self

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "start_ns": self.start_ns, "end_ns": self.end_ns,
                "duration_s": self.duration_s, "tags": dict(self.tags)}

    def __repr__(self):
        return (f"TraceSpan({self.name!r}, trace={self.trace_id}, "
                f"dur={self.duration_s})")


class TraceContext:
    """One request's identity: trace_id + root span + baggage.

    ``baggage`` is the propagated tag set: every span begun through this
    context inherits it AT BEGIN TIME, so a tag added mid-flight (the
    requeue path sets ``requeued=1``) marks all LATER spans without
    rewriting history — exactly what "which spans ran after the
    failover" needs.
    """

    __slots__ = ("trace_id", "root", "baggage")

    def __init__(self, trace_id: str, root: Optional[TraceSpan] = None,
                 baggage: Optional[Dict[str, object]] = None):
        self.trace_id = trace_id
        self.root = root
        self.baggage: Dict[str, object] = dict(baggage or {})

    @property
    def span_id(self) -> Optional[str]:
        return self.root.span_id if self.root is not None else None

    def begin(self, name: str, parent: Optional[TraceSpan] = None,
              **tags) -> TraceSpan:
        """Open a child span (parent defaults to the root span).
        Baggage merges under explicit tags."""
        merged = dict(self.baggage)
        merged.update(tags)
        pid = (parent or self.root)
        return TraceSpan(self.trace_id, name,
                         parent_id=pid.span_id if pid else None,
                         tags=merged)

    def event(self, name: str, **tags) -> TraceSpan:
        """Instantaneous marker span (begin + immediate end)."""
        return self.begin(name, **tags).end()

    def finish(self, **tags) -> None:
        if self.root is not None:
            self.root.end(**tags)

    # -- cross-process propagation (W3C traceparent shape) -------------------
    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id or '0' * 8}-01"

    def baggage_header(self) -> str:
        return ",".join(f"{k}={v}" for k, v in sorted(self.baggage.items()))

    @classmethod
    def from_traceparent(cls, header: str,
                         baggage: Optional[str] = None) -> "TraceContext":
        parts = header.strip().split("-")
        if len(parts) != 4 or parts[0] != "00":
            raise ValueError(f"bad traceparent {header!r}")
        bag: Dict[str, object] = {}
        for item in (baggage or "").split(","):
            if "=" in item:
                k, v = item.split("=", 1)
                bag[k.strip()] = v.strip()
        return cls(parts[1], root=None, baggage=bag)


def new_trace(name: str = "request", **tags) -> TraceContext:
    """Mint a fresh trace: new trace_id + an OPEN root span."""
    trace_id = f"{next(_TRACE_IDS):016x}"
    ctx = TraceContext(trace_id)
    ctx.root = TraceSpan(trace_id, name, tags=tags)
    return ctx


def end_open_spans(spans: Dict[str, TraceSpan], **tags) -> None:
    """Close every open span in a request's span map (abort paths:
    replica death, preemption, deadline expiry) and clear the map."""
    for sp in list(spans.values()):
        sp.end(**tags)
    spans.clear()


class TraceRecorder:
    """Bounded ring of FINISHED spans + the trace-level export surface.

    Chrome trace export maps each trace_id onto its own ``tid`` row, so
    a multi-request dump renders one swimlane per request with the
    queue/admit/prefill/decode/stream decomposition nested inside it.
    """

    def __init__(self, capacity: int = _RECORDER_CAP):
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=max(1, capacity))
        self._dropped = 0
        self._drop_warned = False

    @property
    def dropped(self) -> int:
        """Spans evicted from the bounded ring since the last clear()."""
        return self._dropped

    @property
    def capacity(self) -> int:
        return self._spans.maxlen

    def record(self, span: TraceSpan) -> None:
        warn = False
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
                _trace_metrics()[1].inc()
                if not self._drop_warned:
                    self._drop_warned = warn = True
            self._spans.append(span)
        if warn:
            import logging
            logging.getLogger(__name__).warning(
                "trace recorder full (capacity=%d): spans are being "
                "dropped; raise PADDLE_TRACE_CAP or export more often",
                self._spans.maxlen)
        from .fleet import get_spool
        sp = get_spool()
        if sp is not None:
            sp.span(span.to_dict(), time.time())

    def spans(self, trace_id: Optional[str] = None) -> List[TraceSpan]:
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return sorted(out, key=lambda s: s.start_ns)

    def trace_ids(self) -> List[str]:
        """Distinct trace ids in recording order."""
        seen: "OrderedDict[str, None]" = OrderedDict()
        for s in self.spans():
            seen.setdefault(s.trace_id, None)
        return list(seen)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._dropped = 0
            self._drop_warned = False

    # -- export --------------------------------------------------------------
    def to_chrome(self, trace_id: Optional[str] = None) -> dict:
        """Chrome trace-event JSON (``chrome://tracing`` / Perfetto).

        Complete (``ph: X``) events; ``ts``/``dur`` in microseconds
        relative to the earliest span so the viewer opens at t=0."""
        spans = self.spans(trace_id)
        tid_of = {t: i for i, t in enumerate(
            OrderedDict((s.trace_id, None) for s in spans))}
        t0 = spans[0].start_ns if spans else 0
        events = []
        for s in spans:
            events.append({
                "name": s.name, "ph": "X", "cat": "request",
                "ts": (s.start_ns - t0) / 1e3,
                "dur": ((s.end_ns or s.start_ns) - s.start_ns) / 1e3,
                "pid": 1, "tid": tid_of[s.trace_id],
                "args": {"trace_id": s.trace_id, "span_id": s.span_id,
                         "parent_id": s.parent_id, **s.tags},
            })
        meta = [{"name": "thread_name", "ph": "M", "pid": 1,
                 "tid": tid, "args": {"name": f"trace {t}"}}
                for t, tid in tid_of.items()]
        return {"traceEvents": meta + events,
                "displayTimeUnit": "ms",
                "metadata": {"dropped_spans": self._dropped,
                             "capacity": self._spans.maxlen}}

    def export_chrome(self, path: str,
                      trace_id: Optional[str] = None) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome(trace_id), f)
        return path

    def export_jsonl(self, path: str,
                     trace_id: Optional[str] = None) -> str:
        """One span dict per line (joinable with metric snapshots)."""
        with open(path, "w") as f:
            for s in self.spans(trace_id):
                f.write(json.dumps(s.to_dict()) + "\n")
        return path


_RECORDER = TraceRecorder()


def get_recorder() -> TraceRecorder:
    """The process-wide trace recorder (exporters read this)."""
    return _RECORDER
