"""Inference/serving API.

Reference: paddle/fluid/inference (AnalysisPredictor, analysis_predictor.h:
100) + the paddle.inference python surface (Config, create_predictor,
named input/output handles). TPU-native: the "analysis + optimized
program" stage is the AOT-compiled StableHLO executable written by
paddle_tpu.jit.save; the Predictor is a thin runner over the deserialized
export (XLA did the graph optimization the reference's 250-pass zoo does).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.tensor import Tensor


class Config:
    """paddle.inference.Config analog (prog_file/params_file prefix form)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            self._prefix = prog_file[:-len(".pdmodel")]
        else:
            self._prefix = prog_file
        self._memory_pool_mb = 0
        self._enable_profile = False

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        self._prefix = (prog_file[:-len(".pdmodel")]
                        if prog_file.endswith(".pdmodel") else prog_file)

    def model_dir(self):
        return self._prefix

    # knob parity (XLA owns these decisions on TPU)
    def enable_use_gpu(self, *a, **k):
        return None

    def enable_memory_optim(self, *a, **k):
        return None

    def switch_ir_optim(self, *a, **k):
        return None

    def enable_profile(self):
        self._enable_profile = True

    def disable_glog_info(self):
        return None


class _IOHandle:
    """paddle.inference input/output handle analog (copy_from_cpu /
    copy_to_cpu)."""

    def __init__(self, predictor: "Predictor", name: str, is_input: bool):
        self._p = predictor
        self.name = name
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        assert self._is_input
        self._p._inputs[self.name] = np.asarray(arr)

    def reshape(self, shape):
        return None

    def copy_to_cpu(self) -> np.ndarray:
        assert not self._is_input
        return self._p._outputs[self.name]

    def shape(self):
        src = self._p._inputs if self._is_input else self._p._outputs
        v = src.get(self.name)
        return list(v.shape) if v is not None else None


class Predictor:
    """AnalysisPredictor analog over an AOT export."""

    def __init__(self, config: Config):
        from .. import jit
        self._config = config
        self._layer = jit.load(config.model_dir())
        if not hasattr(self._layer, "_exported"):
            raise ValueError(
                f"{config.model_dir()} is a params-only save; export with "
                f"jit.save(layer, path, input_spec=[...]) for serving")
        specs = self._layer.input_specs()
        self._input_names = [s.get("name") or f"x{i}"
                             for i, s in enumerate(specs)]
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}
        self._output_names: List[str] = []

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> _IOHandle:
        return _IOHandle(self, name, is_input=True)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        if inputs is not None:
            for n, arr in zip(self._input_names, inputs):
                self._inputs[n] = np.asarray(arr)
        args = [self._inputs[n] for n in self._input_names]
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._output_names = [f"out{i}" for i in range(len(outs))]
        self._outputs = {n: np.asarray(o._data)
                         for n, o in zip(self._output_names, outs)}
        if inputs is not None:
            return [self._outputs[n] for n in self._output_names]
        return True

    def get_output_names(self) -> List[str]:
        return list(self._output_names) or ["out0"]

    def get_output_handle(self, name: str) -> _IOHandle:
        return _IOHandle(self, name, is_input=False)


def create_predictor(config: Config) -> Predictor:
    """paddle.inference.create_predictor analog."""
    return Predictor(config)


class BucketBatchingPredictor:
    """Dynamic request batching over an AOT export (the serving-relevant
    analog of AnalysisPredictor's zero-copy batch path,
    analysis_predictor.h:100, rebuilt for XLA's compilation model).

    XLA compiles per shape, so free-form batch sizes would retrace per
    request. Requests are padded up to the nearest BUCKET batch size
    instead: each bucket compiles once, every later request in that bucket
    reuses the executable, and the pad rows are sliced off the outputs.
    """

    def __init__(self, predictor: Predictor, buckets=(1, 2, 4, 8, 16, 32)):
        self._p = predictor
        self.buckets = tuple(sorted(buckets))
        self.max_batch = self.buckets[-1]

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"batch {n} exceeds max bucket {self.max_batch}")

    def run_batch(self, requests: List[List[np.ndarray]]):
        """requests: one [input_arrays] list per request; every array MUST
        carry its batch dim (shape [1, ...] for single items — a bare
        feature vector would be concatenated along the wrong axis).
        Returns one output list per request."""
        if not requests:
            return []
        n = len(requests)
        bucket = self._bucket(n)
        stacked = []
        for i in range(len(requests[0])):
            rows = [np.asarray(r[i]) for r in requests]
            batch = np.concatenate(rows, axis=0)
            pad = bucket * rows[0].shape[0] - batch.shape[0]
            if pad:
                batch = np.concatenate(
                    [batch, np.repeat(batch[-1:], pad, axis=0)], axis=0)
            stacked.append(batch)
        outs = self._p.run(stacked)
        per = outs[0].shape[0] // bucket
        results = []
        for r in range(n):
            results.append([o[r * per:(r + 1) * per] for o in outs])
        return results


from .serving import (ContinuousBatcher, PagedContinuousBatcher,  # noqa: E402
                      Request)
from .gateway import (Gateway, GatewayRequest, Replica,  # noqa: E402
                      ReplicaPool, StreamingSession, TenantQuotas,
                      TokenBucket)

__all__ = ["Config", "Predictor", "BucketBatchingPredictor",
           "ContinuousBatcher", "PagedContinuousBatcher", "Request",
           "Gateway", "GatewayRequest", "Replica", "ReplicaPool",
           "StreamingSession", "TenantQuotas", "TokenBucket",
           "create_predictor"]
