"""Cross-request radix prefix index over the paged KV pool — now tiered.

SGLang's RadixAttention observation, applied to the PagedContinuousBatcher:
a million-user workload shares a handful of system prompts, so the KV rows
for those shared prefixes are recomputed on every admission unless someone
remembers which physical pages already hold them. This module is that
memory — a radix tree at BLOCK granularity (one node == one full
``block_size``-token block == one physical page), host-side only:

  * ``match(tokens)``   — longest cached prefix as a node path; admission
    points the slot's block-table entries at those pages and prefills only
    the suffix (``paged_prefill_into``'s ``dec_base`` append mode).
  * ``pin``/``unpin``   — per-node refcounts. A page referenced by a live
    slot is never evicted; release decrements and stamps LRU recency.
  * ``insert``          — after prefill, the request's full prompt blocks
    are adopted into the tree (page ownership moves from the slot to the
    cache), so the NEXT request with this prefix hits.
  * ``evict(n)``        — LRU eviction of unpinned device chains under page
    pressure; returns the freed physical page ids to the batcher's pool.
    Interior nodes are protected while any device descendant lives (a
    child's rows attend the whole prefix, so ancestors must stay resident).

Tiered residency (CachedAttention/AttentionStore-style hierarchical KV):
each node carries a ``residency`` in the monotone chain
``device -> host -> disk -> gone``. With a ``HostTier`` attached,
``evict()`` DEMOTES the victim's KV rows to a pinned host-DRAM blob (read
back off the pool by the batcher's spill callback) instead of dropping
them; the node stays in the tree, pageless, and a later ``match`` that
lands on it triggers an async ``device_put`` promotion (driven by the
batcher — this module only tracks residency and blob bytes). The host
tier is byte-capacity-bounded (``PADDLE_KV_HOST_GIB``); overflow demotes
host-LRU nodes to an optional ``DiskTier`` behind the same interface, or
drops them. The residency rank is NON-DECREASING with depth along any
root->leaf path (eviction takes deepest device nodes first, promotion
installs top-down), which is what lets ``match`` split any path into a
device prefix + a promotable tail.

Only FULL blocks are cached: a partially-filled page is still being
appended to by its owner and cannot be shared. Generated tokens are
cacheable too — a preempted/failed-over request resumes with
``prompt ⧺ generated`` as its admission ids, and re-matching those blocks
is exactly what makes failover re-prefill cheap.

Routing support: every node carries a chain hash
(``h_i = H(h_{i-1}, block_tokens)``); ``summary()`` exposes the hash set
plus a per-hash residency map so gateway replicas can advertise WHAT they
have cached — and in which tier — without shipping token arrays.
``chain_hashes()`` lets the router compute a request's chain once and find
the deepest advertised match per replica, preferring device-resident
depth. The advertisement is cached and invalidated on every mutation
(insert/evict/demote/promote), so the router never chases dead prefixes.
Hashes are a routing hint only — correctness never depends on them (the
tree itself compares real token blocks).
"""
from __future__ import annotations

import hashlib
import os
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["RadixPrefixCache", "HostTier", "DiskTier", "chain_hashes",
           "blob_nbytes"]

_ROOT_HASH = 0

# residency ranks: monotone non-decreasing with depth along any path
_TIER_RANK = {"device": 0, "host": 1, "disk": 2}


def _block_hash(parent_hash: int, block: Tuple[int, ...]) -> int:
    """Stable 64-bit chain hash of one block given its parent's hash."""
    h = hashlib.blake2b(digest_size=8)
    h.update(int(parent_hash).to_bytes(8, "little", signed=False))
    h.update(np.asarray(block, np.int64).tobytes())
    return int.from_bytes(h.digest(), "little")


def chain_hashes(tokens, block_size: int) -> List[int]:
    """Chain hashes of every FULL block prefix of ``tokens`` — the
    request-side half of the replica prefix-summary protocol."""
    toks = np.asarray(tokens, np.int64).reshape(-1)
    out: List[int] = []
    h = _ROOT_HASH
    for i in range(len(toks) // block_size):
        blk = tuple(int(t) for t in
                    toks[i * block_size:(i + 1) * block_size])
        h = _block_hash(h, blk)
        out.append(h)
    return out


def blob_nbytes(blob) -> int:
    """Total bytes of every ndarray leaf in a spilled KV blob (a pytree of
    lists/tuples/dicts of numpy arrays) — the tier accounting unit."""
    if isinstance(blob, np.ndarray):
        return int(blob.nbytes)
    if isinstance(blob, dict):
        return sum(blob_nbytes(v) for v in blob.values())
    if isinstance(blob, (list, tuple)):
        return sum(blob_nbytes(v) for v in blob)
    return 0


class HostTier:
    """Byte-capacity-bounded host-DRAM blob store for demoted KV blocks.

    The radix tree owns victim selection (LRU over host-resident nodes)
    and the residency state machine; the tier owns storage + byte
    accounting. ``next_tier`` (a :class:`DiskTier`) receives this tier's
    overflow; without one, overflow is dropped (residency ``gone``).
    """

    name = "host"

    def __init__(self, capacity_bytes: int, next_tier: Optional["DiskTier"] = None):
        if capacity_bytes < 1:
            raise ValueError("host tier capacity must be >= 1 byte")
        self.capacity_bytes = int(capacity_bytes)
        self.next_tier = next_tier
        self._blobs: Dict[int, Tuple[object, int]] = {}  # id -> (blob, nbytes)
        self.used_bytes = 0
        self.stored = 0
        self.evicted = 0  # pushed out of THIS tier (to next tier or gone)

    def put(self, key: int, blob) -> int:
        nbytes = blob_nbytes(blob)
        self._blobs[key] = (blob, nbytes)
        self.used_bytes += nbytes
        self.stored += 1
        return nbytes

    def get(self, key: int):
        return self._blobs[key][0]

    def nbytes_of(self, key: int) -> int:
        return self._blobs[key][1]

    def discard(self, key: int) -> int:
        _, nbytes = self._blobs.pop(key)
        self.used_bytes -= nbytes
        return nbytes

    def __contains__(self, key: int) -> bool:
        return key in self._blobs

    def __len__(self) -> int:
        return len(self._blobs)

    def keys(self):
        return self._blobs.keys()


class DiskTier:
    """Disk-backed blob store behind the same interface as HostTier.

    Blobs land as one ``.npz`` file each under ``root`` (flattened with
    positional keys, rebuilt on ``get``). Capacity is byte-bounded like
    the host tier; there is no tier below — overflow is dropped.
    """

    name = "disk"
    next_tier = None

    def __init__(self, root: str, capacity_bytes: int = 16 << 30):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.capacity_bytes = int(capacity_bytes)
        self._files: Dict[int, Tuple[str, int]] = {}  # id -> (path, nbytes)
        self._seq = 0
        self.used_bytes = 0
        self.stored = 0
        self.evicted = 0

    @staticmethod
    def _flatten(blob, prefix: str, out: Dict[str, np.ndarray]):
        if isinstance(blob, np.ndarray):
            out[prefix] = blob
        elif isinstance(blob, dict):
            for k in sorted(blob):
                DiskTier._flatten(blob[k], f"{prefix}.d{k}", out)
        elif isinstance(blob, (list, tuple)):
            for i, v in enumerate(blob):
                DiskTier._flatten(v, f"{prefix}.l{i}", out)

    def put(self, key: int, blob) -> int:
        # keep the logical pytree alongside the arrays: store a flat dict
        # and a rebuild skeleton (array leaves replaced by their flat key)
        flat: Dict[str, np.ndarray] = {}
        self._flatten(blob, "b", flat)
        skeleton = _skeletonize(blob, "b")
        self._seq += 1
        path = os.path.join(self.root, f"kv_{self._seq:08d}.npz")
        np.savez(path, __skeleton__=np.frombuffer(
            repr(skeleton).encode(), dtype=np.uint8), **flat)
        nbytes = sum(int(a.nbytes) for a in flat.values())
        self._files[key] = (path, nbytes)
        self.used_bytes += nbytes
        self.stored += 1
        return nbytes

    def get(self, key: int):
        path, _ = self._files[key]
        with np.load(path) as z:
            skeleton = eval(  # noqa: S307 — repr of plain str/list/dict/tuple
                bytes(z["__skeleton__"]).decode())
            flat = {k: z[k] for k in z.files if k != "__skeleton__"}
        return _unskeletonize(skeleton, flat)

    def nbytes_of(self, key: int) -> int:
        return self._files[key][1]

    def discard(self, key: int) -> int:
        path, nbytes = self._files.pop(key)
        try:
            os.unlink(path)
        except OSError:
            pass
        self.used_bytes -= nbytes
        return nbytes

    def __contains__(self, key: int) -> bool:
        return key in self._files

    def __len__(self) -> int:
        return len(self._files)

    def keys(self):
        return self._files.keys()


def _skeletonize(blob, prefix: str):
    if isinstance(blob, np.ndarray):
        return prefix
    if isinstance(blob, dict):
        return {k: _skeletonize(blob[k], f"{prefix}.d{k}") for k in sorted(blob)}
    if isinstance(blob, (list, tuple)):
        out = [_skeletonize(v, f"{prefix}.l{i}") for i, v in enumerate(blob)]
        return tuple(out) if isinstance(blob, tuple) else out
    return blob


def _unskeletonize(skel, flat: Dict[str, np.ndarray]):
    if isinstance(skel, str) and skel in flat:
        return flat[skel]
    if isinstance(skel, dict):
        return {k: _unskeletonize(v, flat) for k, v in skel.items()}
    if isinstance(skel, tuple):
        return tuple(_unskeletonize(v, flat) for v in skel)
    if isinstance(skel, list):
        return [_unskeletonize(v, flat) for v in skel]
    return skel


class _Node:
    __slots__ = ("key", "page", "parent", "children", "ref", "last_use",
                 "hash", "depth", "residency", "promo", "spin")

    def __init__(self, key: Tuple[int, ...], page: int, parent, hash_: int,
                 depth: int):
        self.key = key              # the block's tokens
        self.page = page            # physical pool row (-1 when off-device)
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.ref = 0                # live slots using this node
        self.last_use = 0           # LRU stamp (monotonic tick)
        self.hash = hash_
        self.depth = depth          # blocks from root (root excluded)
        self.residency = "device"
        self.promo = None           # in-flight promotion record, if any
        self.spin = 0               # session pins (durable-session holds)

    def __repr__(self):            # pragma: no cover - debug aid
        return (f"_Node(depth={self.depth}, page={self.page}, "
                f"ref={self.ref}, spin={self.spin}, tier={self.residency}, "
                f"kids={len(self.children)})")


class RadixPrefixCache:
    """Block-granular radix tree mapping token-block chains to pages,
    with optional host-DRAM (and disk) spill tiers beneath the pool.

    ``host_tier``/``spill``: attach a :class:`HostTier` and a callback
    ``spill(node) -> blob`` (the batcher reads the node's pool rows back
    to pinned numpy) to turn ``evict()`` into demotion. Without a tier
    the eviction semantics are byte-identical to the untiered cache.
    """

    def __init__(self, block_size: int,
                 host_tier: Optional[HostTier] = None,
                 spill: Optional[Callable[["_Node"], object]] = None):
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.block_size = block_size
        self._root = _Node((), -1, None, _ROOT_HASH, 0)
        self._tick = 0
        self._nodes = 0          # every resident node (any tier)
        self._dev_nodes = 0      # device-resident nodes (== pages owned)
        self.host_tier = host_tier
        self._spill = spill
        # cumulative counters (the batcher mirrors them into serving.*)
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.host_hit_tokens = 0   # matched tokens served off host/disk
        self.evictions = 0
        self.demotions = 0
        self.demote_failures = 0
        self.demoted_bytes = 0
        self.promotions = 0        # pages promoted back to device
        self.promoted_bytes = 0
        self.promotion_failures = 0
        self.upgrades = 0          # off-device nodes re-adopted via insert
        self.session_pin_drops = 0  # session-pinned nodes lost anyway
        #   (untiered eviction or a failed spill: chaos/OOM wins; the
        #   session manifest's full-prefill fallback keeps correctness)
        # cached routing advertisement (satellite: invalidate on mutation)
        self._summary_cache: Optional[Dict[str, object]] = None
        self._dirty = True

    # -- bookkeeping ---------------------------------------------------------
    def _touch(self, node: _Node):
        self._tick += 1
        node.last_use = self._tick

    def _invalidate(self):
        self._dirty = True
        self._summary_cache = None

    def __len__(self) -> int:
        return self._nodes

    @property
    def cached_pages(self) -> int:
        return self._dev_nodes

    def pages(self) -> List[int]:
        """Every physical page the cache owns (the audit surface).
        Residency is monotone, so an off-device node has no device
        descendants and its whole subtree can be pruned from the walk."""
        out: List[int] = []
        stack = [n for n in self._root.children.values()
                 if n.residency == "device"]
        while stack:
            n = stack.pop()
            out.append(n.page)
            stack.extend(c for c in n.children.values()
                         if c.residency == "device")
        return out

    def evictable_pages(self) -> int:
        """Pages evict() could free right now — ONE walk sharing evict()'s
        victim rule (a device node frees when its entire device subtree is
        unpinned and promotion-free), so the two can never drift."""
        def walk(n: _Node) -> Tuple[int, bool]:
            count = 0
            free = n.ref == 0 and n.promo is None
            for c in n.children.values():
                if c.residency != "device":
                    continue
                sub, sub_free = walk(c)
                count += sub
                free = free and sub_free
            return count + (1 if free else 0), free

        return sum(walk(c)[0] for c in self._root.children.values()
                   if c.residency == "device")

    # -- the serving hot path ------------------------------------------------
    def _blocks(self, tokens) -> List[Tuple[int, ...]]:
        toks = np.asarray(tokens, np.int64).reshape(-1)
        return [tuple(int(t) for t in
                      toks[i * self.block_size:(i + 1) * self.block_size])
                for i in range(len(toks) // self.block_size)]

    def match(self, tokens, max_blocks: Optional[int] = None) -> List[_Node]:
        """Longest cached prefix of ``tokens`` as the node path (root
        excluded), capped at ``max_blocks``. Does NOT pin — the caller
        pins the path it actually uses. With tiers the path can end in
        off-device nodes; ``split_device`` separates the promotable tail."""
        path: List[_Node] = []
        node = self._root
        for blk in self._blocks(tokens):
            if max_blocks is not None and len(path) >= max_blocks:
                break
            child = node.children.get(blk)
            if child is None:
                break
            path.append(child)
            node = child
        return path

    @staticmethod
    def split_device(path: Sequence[_Node]) -> Tuple[List[_Node], List[_Node]]:
        """Split a match path into (device prefix, off-device tail).
        Monotone residency guarantees the split point is unique."""
        for i, n in enumerate(path):
            if n.residency != "device":
                return list(path[:i]), list(path[i:])
        return list(path), []

    def pin(self, nodes: Iterable[_Node]):
        for n in nodes:
            n.ref += 1
            self._touch(n)

    def unpin(self, nodes: Iterable[_Node]):
        for n in nodes:
            if n.ref <= 0:
                raise RuntimeError(
                    "prefix-cache refcount underflow: unpin of an "
                    "already-free node (double release)")
            n.ref -= 1
            self._touch(n)

    def session_pin(self, nodes: Iterable[_Node]):
        """Durable-session hold: unlike ``pin`` (which freezes pages on
        device), a session pin lets churn demote the chain device -> host
        -> disk but forbids dropping it out of the LAST tier — a paused
        session stays promotable (or at worst disk-resident) until
        ``session_unpin``. No effect on page accounting."""
        for n in nodes:
            n.spin += 1
            self._touch(n)

    def session_unpin(self, nodes: Iterable[_Node]):
        for n in nodes:
            if n.spin <= 0:
                raise RuntimeError(
                    "prefix-cache session-pin underflow: session_unpin of "
                    "an unpinned node (double release)")
            n.spin -= 1
            self._touch(n)

    def insert(self, tokens, pages: Sequence[int],
               start_block: int, n_blocks: int) -> List[_Node]:
        """Adopt blocks [start_block, n_blocks) of ``tokens`` into the
        tree. ``pages[i]`` is the physical page holding block i's rows
        (the slot's block-table row). New nodes take ownership of their
        page and start pinned (ref=1, held by the inserting slot); blocks
        already device-resident are SKIPPED — the slot keeps its private
        copy and the tree keeps its own page (neither is pinned here). An
        off-device node with no promotion in flight is UPGRADED in place:
        it adopts the slot's freshly-prefilled page, its stale blob is
        discarded, and it joins the returned (pinned) list. Returns the
        newly created/upgraded nodes."""
        blocks = self._blocks(tokens)[:n_blocks]
        node = self._root
        created: List[_Node] = []
        for i, blk in enumerate(blocks):
            child = node.children.get(blk)
            if child is None:
                if i < start_block:
                    # the caller said blocks < start_block are already in
                    # the tree (its matched path); a hole here means the
                    # match and insert disagree about tree state
                    raise RuntimeError(
                        "prefix-cache insert: matched prefix missing "
                        "from the tree (match/insert raced?)")
                child = _Node(blk, int(pages[i]), node,
                              _block_hash(node.hash, blk), i + 1)
                child.ref = 1
                node.children[blk] = child
                self._nodes += 1
                self._dev_nodes += 1
                created.append(child)
                self._invalidate()
            elif child.residency != "device" and child.promo is None:
                if i < start_block:
                    raise RuntimeError(
                        "prefix-cache insert: matched device prefix is "
                        "off-device (match/insert raced?)")
                self._discard_blob(child)
                child.page = int(pages[i])
                child.residency = "device"
                child.ref += 1
                self._dev_nodes += 1
                self.upgrades += 1
                created.append(child)
                self._invalidate()
            self._touch(child)
            node = child
        return created

    # -- eviction / demotion -------------------------------------------------
    def evict(self, n_pages: int) -> List[int]:
        """Free up to ``n_pages`` device pages. Victims are LRU device
        nodes with no pinned/promoting device descendants, taken
        deepest-first so an idle chain frees bottom-up. With a host tier
        attached each victim's KV rows are DEMOTED (spilled to a host
        blob; the node stays matchable); without one — or if the spill
        itself fails — the subtree is dropped. Either way the physical
        page ids are returned to the batcher's pool."""
        freed: List[int] = []
        while len(freed) < n_pages:
            victim = self._lru_device_evictable()
            if victim is None:
                break
            page = victim.page
            if self.host_tier is not None and self._spill is not None:
                self._demote(victim)
            else:
                # untiered: victim has no children at all (no device child
                # by the rule, no off-device child without a tier)
                if victim.spin > 0:
                    self.session_pin_drops += 1
                del victim.parent.children[victim.key]
                self._nodes -= 1
                self._dev_nodes -= 1
            self.evictions += 1
            freed.append(page)
            self._invalidate()
        return freed

    def _lru_device_evictable(self) -> Optional[_Node]:
        # Every pin covers a contiguous root-path (admission pins matched
        # prefixes, promotion pins device prefix + tail, insert's new and
        # upgraded nodes extend an already-pinned path), so ref == 0 here
        # implies no pinned/promoting descendant hides in the off-device
        # subtree either — _drop_subtree on a failed demotion stays safe.
        best: Optional[_Node] = None
        stack = [n for n in self._root.children.values()
                 if n.residency == "device"]
        while stack:
            n = stack.pop()
            dev_kids = [c for c in n.children.values()
                        if c.residency == "device"]
            if not dev_kids and n.ref == 0 and n.promo is None:
                if best is None or n.last_use < best.last_use:
                    best = n
            stack.extend(dev_kids)
        return best

    def _demote(self, victim: _Node):
        """device -> host for one node: spill its pool rows to a blob.
        A failed spill (chaos, OOM) drops the subtree instead — pages
        stay clean, the prefix just recomputes next time."""
        from ..resilience.chaos import fault_point
        try:
            fault_point("kv.host_demote")
            blob = self._spill(victim)
        except Exception:
            self.demote_failures += 1
            blob = None
        if blob is None:
            self._drop_subtree(victim)
            return
        victim.page = -1
        victim.residency = "host"
        self._dev_nodes -= 1
        if self._store(self.host_tier, victim, blob):
            self.demotions += 1
            self.demoted_bytes += self.host_tier.nbytes_of(id(victim))
        else:
            self._drop_subtree(victim)

    def _store(self, tier, node: _Node, blob) -> bool:
        """Place a blob in ``tier``, demoting the tier's own LRU overflow
        down-chain (host -> disk -> gone) to make room. False if even
        after overflow eviction the blob cannot fit."""
        nbytes = blob_nbytes(blob)
        while tier.used_bytes + nbytes > tier.capacity_bytes:
            v = self._lru_tier_evictable(tier)
            if v is None:
                break
            self._evict_from_tier(v, tier)
        if tier.used_bytes + nbytes > tier.capacity_bytes:
            return False
        tier.put(id(node), blob)
        node.residency = tier.name
        return True

    def _lru_tier_evictable(self, tier) -> Optional[_Node]:
        """LRU node of ``tier`` whose demotion keeps residency monotone:
        no pinned/promoting state and no child in the SAME tier (deeper
        children already sit in a lower tier or are gone). When the tier
        has no ``next_tier`` eviction means DROP, so session-pinned nodes
        (``spin > 0``) are skipped there — churn can cascade a paused
        session down the tier chain but never out of the last tier."""
        last = tier.next_tier is None
        best: Optional[_Node] = None
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.residency != tier.name or n.ref > 0 or n.promo is not None:
                continue
            if last and n.spin > 0:
                continue
            if id(n) not in tier:
                # mid-transition: _demote/_evict_from_tier flip residency
                # before _store lands the blob — the node being stored
                # must not be picked as its own overflow victim
                continue
            if any(c.residency == tier.name for c in n.children.values()):
                continue
            if best is None or n.last_use < best.last_use:
                best = n
        return best

    def _evict_from_tier(self, node: _Node, tier):
        """Push one node out of ``tier``: down to ``next_tier`` if it fits,
        else gone (subtree dropped)."""
        tier.evicted += 1
        nxt = tier.next_tier
        if nxt is not None:
            blob = tier.get(id(node))
            tier.discard(id(node))
            node.residency = "_moving"  # off-tier while _store re-homes it
            if self._store(nxt, node, blob):
                self._invalidate()
                return
            node.residency = tier.name  # restore for a clean subtree drop
            tier.put(id(node), blob)
            tier.stored -= 1  # the put above is a restore, not a new store
        self._drop_subtree(node)

    def _drop_subtree(self, node: _Node):
        """Remove a node and everything below it from the tree, returning
        blob bytes to their tiers. Never called with device descendants
        (monotone residency) — device pages are never dropped here."""
        stack = [node]
        order: List[_Node] = []
        while stack:
            n = stack.pop()
            order.append(n)
            stack.extend(n.children.values())
        for n in order:
            if n.residency == "device":
                self._dev_nodes -= 1
            else:
                self._discard_blob(n)
            if n.spin > 0:
                self.session_pin_drops += 1
            self._nodes -= 1
        del node.parent.children[node.key]
        self._invalidate()

    def _tier_of(self, node: _Node):
        t = self.host_tier
        while t is not None:
            if t.name == node.residency:
                return t
            t = t.next_tier
        return None

    def _discard_blob(self, node: _Node):
        tier = self._tier_of(node)
        if tier is not None and id(node) in tier:
            tier.discard(id(node))

    # -- promotion bookkeeping (the batcher drives the async transfer) ------
    def node_blob(self, node: _Node):
        """The spilled KV blob backing an off-device node."""
        tier = self._tier_of(node)
        if tier is None:
            raise KeyError(f"node {node!r} has no tier blob")
        return tier.get(id(node))

    def promote_node(self, node: _Node, page: int, nbytes: int = 0):
        """host/disk -> device: the batcher landed the node's rows in pool
        ``page``; drop the blob and flip residency."""
        self._discard_blob(node)
        node.page = int(page)
        node.residency = "device"
        self._dev_nodes += 1
        self.promotions += 1
        self.promoted_bytes += int(nbytes)
        self._touch(node)
        self._invalidate()

    # -- the routing surface -------------------------------------------------
    def summary(self) -> Dict[str, object]:
        """Hashed prefix advertisement for the gateway router:
        ``{"block_size": B, "hashes": {chain_hash: depth_blocks},
        "tiers": {chain_hash: residency}}``. Cached; every mutation
        (insert/evict/demote/promote) invalidates it, so evicted chains
        vanish from routing immediately, not at the next insert."""
        if not self._dirty and self._summary_cache is not None:
            return self._summary_cache
        hashes: Dict[int, int] = {}
        tiers: Dict[int, str] = {}
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            hashes[n.hash] = n.depth
            tiers[n.hash] = n.residency
            stack.extend(n.children.values())
        self._summary_cache = {"block_size": self.block_size,
                               "hashes": hashes, "tiers": tiers}
        self._dirty = False
        return self._summary_cache

    # -- audits / stats ------------------------------------------------------
    def audit_tiers(self) -> Dict[str, int]:
        """Prove tier byte accounting leaks zero: every off-device node
        has exactly one blob in its tier, every tier blob belongs to a
        live node, and per-tier used_bytes equals the sum over live
        blobs. Raises on any mismatch."""
        by_tier: Dict[str, Dict[int, _Node]] = {}
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.residency != "device":
                by_tier.setdefault(n.residency, {})[id(n)] = n
        report: Dict[str, int] = {}
        tier = self.host_tier
        while tier is not None:
            nodes = by_tier.pop(tier.name, {})
            keys = set(tier.keys())
            if keys != set(nodes):
                raise RuntimeError(
                    f"kv {tier.name}-tier leak: {len(keys - set(nodes))} "
                    f"orphan blobs, {len(set(nodes) - keys)} blobless nodes")
            total = sum(tier.nbytes_of(k) for k in keys)
            if total != tier.used_bytes:
                raise RuntimeError(
                    f"kv {tier.name}-tier byte drift: accounted "
                    f"{tier.used_bytes} != live {total}")
            report[f"{tier.name}_bytes"] = tier.used_bytes
            report[f"{tier.name}_nodes"] = len(keys)
            tier = tier.next_tier
        if by_tier:
            raise RuntimeError(
                f"kv tier leak: nodes resident in unattached tiers "
                f"{sorted(by_tier)}")
        return report

    def session_pinned_nodes(self) -> int:
        count = 0
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.spin > 0:
                count += 1
        return count

    def stats(self) -> Dict[str, int]:
        host = self.host_tier
        disk = host.next_tier if host is not None else None
        return {"nodes": self._nodes,
                "cached_pages": self._dev_nodes,
                "hit_tokens": self.hit_tokens,
                "miss_tokens": self.miss_tokens,
                "host_hit_tokens": self.host_hit_tokens,
                "evictions": self.evictions,
                "demotions": self.demotions,
                "demote_failures": self.demote_failures,
                "demoted_bytes": self.demoted_bytes,
                "promotions": self.promotions,
                "promoted_bytes": self.promoted_bytes,
                "promotion_failures": self.promotion_failures,
                "upgrades": self.upgrades,
                "session_pinned_nodes": self.session_pinned_nodes(),
                "session_pin_drops": self.session_pin_drops,
                "host_nodes": len(host) if host is not None else 0,
                "host_bytes": host.used_bytes if host is not None else 0,
                "disk_nodes": len(disk) if disk is not None else 0,
                "disk_bytes": disk.used_bytes if disk is not None else 0}
