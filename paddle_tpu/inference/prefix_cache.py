"""Cross-request radix prefix index over the paged KV pool.

SGLang's RadixAttention observation, applied to the PagedContinuousBatcher:
a million-user workload shares a handful of system prompts, so the KV rows
for those shared prefixes are recomputed on every admission unless someone
remembers which physical pages already hold them. This module is that
memory — a radix tree at BLOCK granularity (one node == one full
``block_size``-token block == one physical page), host-side only:

  * ``match(tokens)``   — longest cached prefix as a node path; admission
    points the slot's block-table entries at those pages and prefills only
    the suffix (``paged_prefill_into``'s ``dec_base`` append mode).
  * ``pin``/``unpin``   — per-node refcounts. A page referenced by a live
    slot is never evicted; release decrements and stamps LRU recency.
  * ``insert``          — after prefill, the request's full prompt blocks
    are adopted into the tree (page ownership moves from the slot to the
    cache), so the NEXT request with this prefix hits.
  * ``evict(n)``        — LRU eviction of unpinned LEAF nodes under page
    pressure; returns the freed physical page ids to the batcher's pool.
    Interior nodes are protected while any descendant lives (a child's
    rows attend the whole prefix, so ancestors must stay resident).

Only FULL blocks are cached: a partially-filled page is still being
appended to by its owner and cannot be shared. Generated tokens are
cacheable too — a preempted/failed-over request resumes with
``prompt ⧺ generated`` as its admission ids, and re-matching those blocks
is exactly what makes failover re-prefill cheap.

Routing support: every node carries a chain hash
(``h_i = H(h_{i-1}, block_tokens)``); ``summary()`` exposes the hash set
so gateway replicas can advertise WHAT they have cached without shipping
token arrays, and ``chain_hashes()`` lets the router compute a request's
chain once and find the deepest advertised match per replica. Hashes are
a routing hint only — correctness never depends on them (the tree itself
compares real token blocks).
"""
from __future__ import annotations

import hashlib
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["RadixPrefixCache", "chain_hashes"]

_ROOT_HASH = 0


def _block_hash(parent_hash: int, block: Tuple[int, ...]) -> int:
    """Stable 64-bit chain hash of one block given its parent's hash."""
    h = hashlib.blake2b(digest_size=8)
    h.update(int(parent_hash).to_bytes(8, "little", signed=False))
    h.update(np.asarray(block, np.int64).tobytes())
    return int.from_bytes(h.digest(), "little")


def chain_hashes(tokens, block_size: int) -> List[int]:
    """Chain hashes of every FULL block prefix of ``tokens`` — the
    request-side half of the replica prefix-summary protocol."""
    toks = np.asarray(tokens, np.int64).reshape(-1)
    out: List[int] = []
    h = _ROOT_HASH
    for i in range(len(toks) // block_size):
        blk = tuple(int(t) for t in
                    toks[i * block_size:(i + 1) * block_size])
        h = _block_hash(h, blk)
        out.append(h)
    return out


class _Node:
    __slots__ = ("key", "page", "parent", "children", "ref", "last_use",
                 "hash", "depth")

    def __init__(self, key: Tuple[int, ...], page: int, parent, hash_: int,
                 depth: int):
        self.key = key              # the block's tokens
        self.page = page            # physical pool row holding its KV
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.ref = 0                # live slots using this node
        self.last_use = 0           # LRU stamp (monotonic tick)
        self.hash = hash_
        self.depth = depth          # blocks from root (root excluded)

    def __repr__(self):            # pragma: no cover - debug aid
        return (f"_Node(depth={self.depth}, page={self.page}, "
                f"ref={self.ref}, kids={len(self.children)})")


class RadixPrefixCache:
    """Block-granular radix tree mapping token-block chains to pages."""

    def __init__(self, block_size: int):
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.block_size = block_size
        self._root = _Node((), -1, None, _ROOT_HASH, 0)
        self._tick = 0
        self._nodes = 0
        # cumulative counters (the batcher mirrors them into serving.*)
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.evictions = 0

    # -- bookkeeping ---------------------------------------------------------
    def _touch(self, node: _Node):
        self._tick += 1
        node.last_use = self._tick

    def __len__(self) -> int:
        return self._nodes

    @property
    def cached_pages(self) -> int:
        return self._nodes

    def pages(self) -> List[int]:
        """Every physical page the cache owns (the audit surface)."""
        out: List[int] = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            out.append(n.page)
            stack.extend(n.children.values())
        return out

    def evictable_pages(self) -> int:
        """Pages evict() could free right now: nodes whose SUBTREE holds
        no pinned node (an unpinned chain frees bottom-up)."""
        def free_below(n: _Node) -> int:
            total = 0
            for c in n.children.values():
                sub = free_below(c)
                if sub < 0 or c.ref > 0:
                    return -1 if n is not self._root else total
                total += sub + 1
            return total
        # count subtrees that are entirely unpinned
        total = 0
        for c in self._root.children.values():
            sub = self._count_unpinned(c)
            total += sub
        return total

    def _count_unpinned(self, n: _Node) -> int:
        """Nodes in n's subtree removable by repeated unpinned-leaf
        eviction: the node itself counts only if it and everything below
        it is unpinned (a pinned descendant protects the whole chain)."""
        total = 0
        all_free = n.ref == 0
        for c in n.children.values():
            sub = self._count_unpinned(c)
            total += sub
            if c.ref > 0 or sub < self._subtree_size(c):
                all_free = False
        return total + (1 if all_free else 0)

    def _subtree_size(self, n: _Node) -> int:
        return 1 + sum(self._subtree_size(c) for c in n.children.values())

    # -- the serving hot path ------------------------------------------------
    def _blocks(self, tokens) -> List[Tuple[int, ...]]:
        toks = np.asarray(tokens, np.int64).reshape(-1)
        return [tuple(int(t) for t in
                      toks[i * self.block_size:(i + 1) * self.block_size])
                for i in range(len(toks) // self.block_size)]

    def match(self, tokens, max_blocks: Optional[int] = None) -> List[_Node]:
        """Longest cached prefix of ``tokens`` as the node path (root
        excluded), capped at ``max_blocks``. Does NOT pin — the caller
        pins the path it actually uses."""
        path: List[_Node] = []
        node = self._root
        for blk in self._blocks(tokens):
            if max_blocks is not None and len(path) >= max_blocks:
                break
            child = node.children.get(blk)
            if child is None:
                break
            path.append(child)
            node = child
        return path

    def pin(self, nodes: Iterable[_Node]):
        for n in nodes:
            n.ref += 1
            self._touch(n)

    def unpin(self, nodes: Iterable[_Node]):
        for n in nodes:
            if n.ref <= 0:
                raise RuntimeError(
                    "prefix-cache refcount underflow: unpin of an "
                    "already-free node (double release)")
            n.ref -= 1
            self._touch(n)

    def insert(self, tokens, pages: Sequence[int],
               start_block: int, n_blocks: int) -> List[_Node]:
        """Adopt blocks [start_block, n_blocks) of ``tokens`` into the
        tree. ``pages[i]`` is the physical page holding block i's rows
        (the slot's block-table row). New nodes take ownership of their
        page and start pinned (ref=1, held by the inserting slot); blocks
        already present are SKIPPED — the slot keeps its private copy and
        the tree keeps its own page (neither is pinned here). Returns the
        newly created (adopted) nodes."""
        blocks = self._blocks(tokens)[:n_blocks]
        node = self._root
        created: List[_Node] = []
        for i, blk in enumerate(blocks):
            child = node.children.get(blk)
            if child is None:
                if i < start_block:
                    # the caller said blocks < start_block are already in
                    # the tree (its matched path); a hole here means the
                    # match and insert disagree about tree state
                    raise RuntimeError(
                        "prefix-cache insert: matched prefix missing "
                        "from the tree (match/insert raced?)")
                child = _Node(blk, int(pages[i]), node,
                              _block_hash(node.hash, blk), i + 1)
                child.ref = 1
                node.children[blk] = child
                self._nodes += 1
                created.append(child)
            self._touch(child)
            node = child
        return created

    def evict(self, n_pages: int) -> List[int]:
        """Free up to ``n_pages`` pages by removing LRU unpinned leaves
        (bottom-up, so an idle chain frees deepest-first). Returns the
        freed physical page ids."""
        freed: List[int] = []
        while len(freed) < n_pages:
            victim = self._lru_unpinned_leaf()
            if victim is None:
                break
            del victim.parent.children[victim.key]
            self._nodes -= 1
            self.evictions += 1
            freed.append(victim.page)
        return freed

    def _lru_unpinned_leaf(self) -> Optional[_Node]:
        best: Optional[_Node] = None
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if not n.children and n.ref == 0:
                if best is None or n.last_use < best.last_use:
                    best = n
            stack.extend(n.children.values())
        return best

    # -- the routing surface -------------------------------------------------
    def summary(self) -> Dict[str, object]:
        """Hashed prefix advertisement for the gateway router:
        ``{"block_size": B, "hashes": {chain_hash: depth_blocks}}``."""
        hashes: Dict[int, int] = {}
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            hashes[n.hash] = n.depth
            stack.extend(n.children.values())
        return {"block_size": self.block_size, "hashes": hashes}

    def stats(self) -> Dict[str, int]:
        return {"nodes": self._nodes,
                "cached_pages": self._nodes,
                "hit_tokens": self.hit_tokens,
                "miss_tokens": self.miss_tokens,
                "evictions": self.evictions}
