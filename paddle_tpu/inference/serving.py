"""Continuous batching over the KV-cache decode step.

Reference surface: the serving loop the reference builds around
AnalysisPredictor + block_multihead_attention (dynamic request admission
into a running decode batch). TPU-first design: XLA wants ONE static
shape, so the batcher owns `max_batch` SLOTS — a fixed [L, 2, B, H, S, D]
cache — and the host-side scheduler admits pending requests into free
slots at step boundaries, evicts finished ones, and steps every slot
through one compiled decode executable. Inactive slots decode garbage
into a scratch row that admission's prefill overwrites before any real
read (causality: a slot's attention never reads rows past its own t), so
no per-occupancy recompilation ever happens.
"""
from __future__ import annotations

import os as _os
import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

__all__ = ["ContinuousBatcher", "PagedContinuousBatcher", "Request"]


@dataclass
class Request:
    rid: int
    prompt: np.ndarray          # [s] int64
    max_new_tokens: int
    tokens: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    # explicit flag: a PREEMPTED request also has slot None + partial
    # tokens while it waits for re-admission — it is not done
    finished: bool = False
    submit_t: float = 0.0       # perf_counter at submit (TTFT anchor)
    deadline_t: Optional[float] = None  # perf_counter; None = no deadline
    # propagated request trace (observability.trace_context): the
    # gateway mints it; the batcher opens admit/prefill/decode spans
    # under it; ``spans`` holds the OPEN ones so abort paths can close
    trace: Optional[object] = None
    spans: Dict[str, object] = field(default_factory=dict)

    @property
    def done(self) -> bool:
        return self.finished


class _ServingStats:
    """Per-batcher serving telemetry, reported TWICE: local counters keep
    the ``stats()`` contract exact per instance (and resettable after
    warmup), while every event also lands in the process-wide metrics
    registry as ``serving_*`` series labeled by engine — the pipe the
    Prometheus/JSONL exporters and ``tools/telemetry_dump.py`` read."""

    def __init__(self, engine: str):
        from .. import observability as obs
        reg = obs.get_registry()
        eng = ("engine",)

        def c(name, help):
            return reg.counter(name, help, labelnames=eng).labels(
                engine=engine)

        def g(name, help):
            return reg.gauge(name, help, labelnames=eng).labels(
                engine=engine)

        def h(name, help):
            return reg.histogram(name, help, labelnames=eng).labels(
                engine=engine)

        self.requests = c("serving_requests_total", "requests submitted")
        self.admissions = c("serving_admissions_total",
                            "requests admitted into slots")
        self.completions = c("serving_completions_total",
                             "requests finished")
        self.preempt_c = c("serving_preemptions_total",
                           "requests preempted back to the queue")
        self.tokens_c = c("serving_tokens_total", "tokens generated")
        self.steps_c = c("serving_steps_total", "decode steps")
        self.blocks_c = c("serving_decode_blocks_total",
                          "K-step decode blocks dispatched")
        self.queue_depth = g("serving_queue_depth",
                             "pending requests right now")
        self.active_slots = g("serving_active_slots",
                              "occupied slots right now")
        self.ttft = h("serving_ttft_seconds",
                      "submit to first generated token")
        self.step_seconds = h("serving_step_seconds",
                              "one decode dispatch wall time")
        self.token_seconds = h("serving_per_token_seconds",
                               "per-token decode latency")
        self.shed_c = c("requests_shed_total",
                        "requests rejected at admission (queue full)")
        self.expired_c = c("serving_deadline_expired_total",
                           "requests abandoned on an expired deadline")
        self.reset()

    def reset(self):
        """Re-baseline the per-instance counters (the registry series are
        process-cumulative by design and keep running)."""
        self.steps = 0
        self.tokens = 0
        self.occupancy_sum = 0
        self.completed = 0
        self.preempted = 0
        self.cachekv_elems = 0
        self.cachekv_clipped = 0
        self.warned_cachekv_clip = False
        self.decode_blocks = 0
        self.shed = 0
        self.expired = 0
        self.t0 = _time.perf_counter()

    # -- events -------------------------------------------------------------
    def on_submit(self, pending_now: int):
        self.requests.inc()
        self.queue_depth.set(pending_now)

    def on_admit(self):
        self.admissions.inc()

    def on_token(self, req: Request):
        self.tokens += 1
        self.tokens_c.inc()
        if len(req.tokens) == 1 and req.submit_t:
            self.ttft.observe(_time.perf_counter() - req.submit_t)

    def on_step(self, substeps: int = 1):
        self.steps += substeps
        self.steps_c.inc(substeps)

    def on_occupancy(self, n: int):
        self.occupancy_sum += n

    def on_decode_time(self, dt: float, substeps: int = 1,
                       tokens: int = 0):
        self.step_seconds.observe(dt)
        self.token_seconds.observe(dt / max(substeps, 1))
        if tokens:
            # join the dispatch against the roofline's serving token
            # bound (roofline.serving.* gauges; no-op without a model)
            from ..observability import roofline_attr
            roofline_attr.observe_serving_step(dt, tokens)

    def on_complete(self):
        self.completed += 1
        self.completions.inc()

    def on_preempt(self):
        self.preempted += 1
        self.preempt_c.inc()

    def on_decode_block(self):
        self.decode_blocks += 1
        self.blocks_c.inc()

    def on_shed(self):
        self.shed += 1
        self.shed_c.inc()

    def on_deadline_expired(self):
        self.expired += 1
        self.expired_c.inc()

    def on_cachekv(self, clipped: int, total: int):
        self.cachekv_elems += total
        self.cachekv_clipped += clipped

    def set_gauges(self, pending: int, active: int):
        self.queue_depth.set(pending)
        self.active_slots.set(active)

    # -- the stats() contract -----------------------------------------------
    def snapshot(self, max_batch: int, pending: int,
                 active: int) -> Dict[str, float]:
        dt = max(_time.perf_counter() - self.t0, 1e-9)
        steps = max(self.steps, 1)
        return {
            "steps": self.steps,
            "generated_tokens": self.tokens,
            "tokens_per_sec": self.tokens / dt,
            "mean_active_slots": self.occupancy_sum / steps,
            "slot_utilization": self.occupancy_sum / steps / max_batch,
            "completed_requests": self.completed,
            "preemptions": self.preempted,
            "pending_now": pending,
            "active_now": active,
            "elapsed_s": dt,
            "cachekv_clip_rate": (self.cachekv_clipped
                                  / max(self.cachekv_elems, 1)),
            "decode_blocks": self.decode_blocks,
            "requests_shed": self.shed,
            "deadline_expired": self.expired,
        }


class _BatcherBase:
    """Request lifecycle shared by the dense-slot and paged batchers:
    FIFO submission, finish-on-EOS-or-budget, result retrieval, deadline
    expiry + load shedding, health reporting, and the drive loop.
    Subclasses own the cache layout and implement ``_release_slot(slot)``
    (return the slot's memory to their pool) plus ``_step_impl()`` (one
    engine step; the base ``step()`` wraps it with deadline/health/chaos
    policy)."""

    _engine = "serving"        # registry label; subclasses override

    def _init_queues(self, max_queue_depth: Optional[int] = None,
                     default_deadline_s: Optional[float] = None):
        self._slot_req: Dict[int, Request] = {}
        self._pending: List[Request] = []
        self._finished: Dict[int, Request] = {}
        self._failed: Dict[int, Exception] = {}
        self._next_rid = 0  # tpu-lint: disable=CC404 (ctor-time init)
        # intake lock: serializes submit-side producers (a fronting RPC
        # layer may call submit/cancel off-thread) against the step
        # loop's queue harvest. Slot/device/cache state stays step-loop-
        # owned and is deliberately NOT under this lock — holding it
        # across prefill/decode would block every submitter for a full
        # device dispatch (CC402). Reentrant: submit and the step loop
        # both nest _expire_pending.
        from ..utils.locks import TracedRLock
        self._intake = TracedRLock("Batcher._intake")
        self._max_queue_depth = max_queue_depth
        self._default_deadline_s = default_deadline_s
        # serving observability (reference analog: the predictor's
        # benchmark counters): per-instance totals via stats(), process-
        # wide serving_* series via the observability registry
        self._tele = _ServingStats(self._engine)
        from ..resilience.recovery import HealthStateMachine
        self.health = HealthStateMachine(
            capacity=max_queue_depth or 2 * self.max_batch,
            engine=self._engine)

    def reset_stats(self):
        """Zero the counters and restart the clock — call after warmup so
        steady-state throughput excludes compile time."""
        self._tele.reset()

    def stats(self) -> Dict[str, float]:
        """Throughput/occupancy counters for monitoring: decode steps,
        generated tokens, tokens/sec since construction, mean active
        slots per step, utilization (active/max_batch), completions,
        preemptions, queue depth right now."""
        return self._tele.snapshot(self.max_batch, len(self._pending),
                                   len(self._slot_req))

    # back-compat handles: these private counters moved into _ServingStats;
    # external probes (tests, notebooks) still reach them at the old names
    @property
    def _stat_cachekv_elems(self) -> int:
        return self._tele.cachekv_elems

    @property
    def _stat_cachekv_clipped(self) -> int:
        return self._tele.cachekv_clipped

    @property
    def _warned_cachekv_clip(self) -> bool:
        return self._tele.warned_cachekv_clip

    @_warned_cachekv_clip.setter
    def _warned_cachekv_clip(self, v: bool):
        self._tele.warned_cachekv_clip = v

    @staticmethod
    def _check_window(cfg, s_max: int):
        if s_max > cfg.max_position_embeddings:
            raise ValueError(f"s_max={s_max} exceeds "
                             f"max_position_embeddings="
                             f"{cfg.max_position_embeddings}")

    def _validate(self, prompt: np.ndarray, max_new_tokens: int):
        if max_new_tokens < 1:
            # admission emits one token from the prefill logits, so a
            # zero-token request cannot match generate(max_new_tokens=0)
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > self.s_max:
            raise ValueError(f"prompt {len(prompt)} + {max_new_tokens} "
                             f"exceeds slot capacity {self.s_max}")

    def submit(self, prompt_ids, max_new_tokens: int,
               deadline_s: Optional[float] = None,
               trace: Optional[object] = None) -> int:
        """Queue a request. Raises typed ``Overloaded`` when the pending
        queue is at ``max_queue_depth`` (load shedding — a fronting layer
        maps it to 429). ``deadline_s`` (or the batcher's default) bounds
        the request's total latency: an expired request is abandoned at
        the next step boundary and its result() raises
        ``DeadlineExceeded``. ``trace`` (a ``TraceContext``) propagates a
        fronting layer's request trace: the batcher opens its
        admit/prefill/decode spans under it."""
        prompt = np.asarray(prompt_ids, np.int64).reshape(-1)
        self._validate(prompt, max_new_tokens)
        # purge already-expired queued requests BEFORE the capacity
        # check: a dead-on-arrival queue entry must not cause a shed
        # (shed and deadline_expired stay disjoint per request)
        self._expire_pending()
        shed_depth = None
        with self._intake:
            if self._max_queue_depth is not None \
                    and len(self._pending) >= self._max_queue_depth:
                shed_depth = len(self._pending)
            else:
                rid = self._next_rid
                self._next_rid += 1
                budget = deadline_s if deadline_s is not None \
                    else self._default_deadline_s
                now = _time.perf_counter()
                self._pending.append(Request(
                    rid, prompt, max_new_tokens, submit_t=now,
                    deadline_t=None if budget is None else now + budget,
                    trace=trace))
                depth = len(self._pending)
        # telemetry/health callbacks run OUTSIDE _intake (CC403): they
        # can re-enter the batcher or block on an exporter.
        if shed_depth is not None:
            from ..resilience.recovery import Overloaded
            self._tele.on_shed()
            self.health.on_shed()
            raise Overloaded(
                f"pending queue at capacity "
                f"({shed_depth}/{self._max_queue_depth})")
        self._tele.on_submit(depth)
        return rid

    # -- request-trace hooks (observability.trace_context) -------------------
    # All no-ops when the request carries no TraceContext (standalone
    # batchers, tracing disabled): one attribute check per event.
    def _trace_admit_begin(self, req: Request):
        if req.trace is not None:
            tags = {"engine": self._engine}
            group = getattr(self, "shard_group", None)
            if group is not None:
                # tensor-parallel group: name the members so the
                # waterfall shows WHICH shards this admit rode on
                tags["tp_group"] = group.name
                tags["tp_members"] = ",".join(group.members)
            req.spans["admit"] = req.trace.begin("admit", **tags)

    def _trace_prefill_begin(self, req: Request):
        if req.trace is not None:
            tags = {}
            if req.tokens:
                # preemption resume: this prefill recomputes KV the
                # eviction threw away (prompt + already-decoded tokens)
                tags["evict_recompute"] = 1
            elif req.trace.baggage.get("requeued"):
                # failover survivor: the prompt re-prefill duplicates
                # work the dead/drained replica already did — the ledger
                # costs this interval as waste.requeue_recompute
                tags["requeue_recompute"] = 1
                if req.trace.baggage.get("drained"):
                    # administrative drain, not a death — same recompute
                    # cost, different cause
                    tags["drain_recompute"] = 1
            req.spans["prefill"] = req.trace.begin(
                "prefill", parent=req.spans.get("admit"), **tags)

    def _trace_prefill_end(self, req: Request, **tags):
        sp = req.spans.pop("prefill", None)
        if sp is not None:
            sp.end(**tags)

    def _trace_admit_end(self, req: Request, slot: int):
        """Close the admit span and open the decode span (which stays
        open across batched steps until the request finishes)."""
        sp = req.spans.pop("admit", None)
        if sp is not None:
            sp.end(slot=slot)
        if req.trace is not None:
            req.spans["decode"] = req.trace.begin("decode", slot=slot)

    def _trace_close(self, req: Request, **tags):
        if req.spans:
            from ..observability.trace_context import end_open_spans
            end_open_spans(req.spans, **tags)

    def _fail(self, req: Request, exc: Exception):
        req.slot = None
        req.finished = True
        self._trace_close(req, error=type(exc).__name__)
        self._failed[req.rid] = exc

    def _expire_pending(self):
        """Abandon QUEUED requests whose deadline passed. Runs both at
        the step boundary and at submit time (before the capacity
        check), so an expired queue entry frees its spot instead of
        pushing a live request into a shed."""
        from ..resilience.recovery import DeadlineExceeded
        now = _time.perf_counter()
        with self._intake:
            expired = [r for r in self._pending
                       if r.deadline_t is not None and now > r.deadline_t]
            for req in expired:
                self._pending.remove(req)
        # fail/notify outside _intake: _fail closes the request trace and
        # on_deadline_expired is a telemetry callback (CC403)
        for req in expired:
            self._fail(req, DeadlineExceeded(
                f"request {req.rid} expired while queued"))
            self._tele.on_deadline_expired()

    def _expire_deadlines(self):
        """Abandon requests whose deadline passed — pending ones silently
        leave the queue, active ones release their slot (and cache
        memory) so live traffic gets the capacity back."""
        from ..resilience.recovery import DeadlineExceeded
        now = _time.perf_counter()

        def expired(r: Request) -> bool:
            return r.deadline_t is not None and now > r.deadline_t

        self._expire_pending()
        for slot, req in list(self._slot_req.items()):
            if expired(req):
                del self._slot_req[slot]
                self._release_slot(slot)
                self._fail(req, DeadlineExceeded(
                    f"request {req.rid} expired after "
                    f"{len(req.tokens)} tokens"))
                self._tele.on_deadline_expired()
        adm = getattr(self, "_admitting", None)
        if adm is not None and expired(adm["req"]):
            # in-flight fused admission: pages back to the pool
            self._release_row(adm["row"])
            self._free_slots.append(adm["slot"])
            self._admitting = None
            self._fail(adm["req"], DeadlineExceeded(
                f"request {adm['req'].rid} expired during admission"))
            self._tele.on_deadline_expired()

    def step(self) -> List[int]:
        """Expire deadlines, then run one engine step (subclass
        ``_step_impl``); feeds the health state machine and the
        ``serving.step`` chaos point. Returns rids finishing during THIS
        call."""
        self._expire_deadlines()
        try:
            from ..resilience.chaos import fault_point
            fault_point("serving.step")
            group = getattr(self, "shard_group", None)
            if group is not None:
                # tensor-parallel shard group: a dead member means this
                # engine's weights/KV shard is gone — TPMemberDied is
                # non-retryable by design (the gateway declares the
                # whole group dead and requeues token-exact)
                group.heartbeat()
            finished = self._step_impl()
        except Exception:
            self.health.on_step_error()
            raise
        self.health.on_step_ok(len(self._pending))
        from ..observability.fleet import autospool_tick
        autospool_tick()   # rank-sharded metrics spool; no-op unarmed
        return finished

    def _pick(self, logits_np):
        """Next-token selection (greedy or sampled) on host logits [B, V];
        shares the model's sampling semantics."""
        from ..models.gpt import GPT2ForCausalLM
        return GPT2ForCausalLM._select_token(
            logits_np, self._do_sample, self._temperature, self._top_k,
            self._top_p, self._rng)

    def _maybe_finish(self, req: Request, tok: int) -> bool:
        if (tok == self.eos_id if self.eos_id is not None else False) \
                or len(req.tokens) >= req.max_new_tokens:
            slot = req.slot
            req.slot = None
            req.finished = True
            del self._slot_req[slot]
            self._release_slot(slot)
            self._trace_close(req, tokens=len(req.tokens))
            self._finished[req.rid] = req
            self._tele.on_complete()
            return True
        return False

    def _release_slot(self, slot: int):          # pragma: no cover
        raise NotImplementedError

    def result(self, rid: int) -> np.ndarray:
        """Full sequence (prompt + generated) of a finished request.
        Raises the request's typed failure (``DeadlineExceeded``) if it
        was abandoned instead of completed."""
        if rid in self._failed:
            raise self._failed[rid]
        req = self._finished[rid]
        return np.concatenate([req.prompt, np.asarray(req.tokens)])

    def pop_result(self, rid: int) -> np.ndarray:
        """result() + release the request's memory — long-lived batchers
        must pop (or use run_until_done, which pops) or _finished grows
        with every request ever served."""
        if rid in self._failed:
            raise self._failed.pop(rid)
        out = self.result(rid)
        del self._finished[rid]
        return out

    def _has_work(self) -> bool:
        return bool(self._pending or self._slot_req)

    def run_until_done(self, max_steps: int = 10000) -> Dict[int, np.ndarray]:
        """Drive until every submitted request completes; returns (and
        releases) exactly THIS run's results. Raises if the step budget
        is exhausted with work still pending/active — a silent partial
        dict would read as lost requests."""
        done: List[int] = []
        for _ in range(max_steps):
            done += self.step()
            if not self._has_work():
                break
        else:
            raise RuntimeError(
                f"run_until_done: {len(self._pending)} pending / "
                f"{len(self._slot_req)} active requests remain after "
                f"{max_steps} steps")
        return {rid: self.pop_result(rid) for rid in done}

    @property
    def active(self) -> int:
        return len(self._slot_req)

    @property
    def pending(self) -> int:
        return len(self._pending)

    def request(self, rid: int) -> Optional[Request]:
        """The live ``Request`` record for ``rid`` — queued, active,
        mid-admission, or finished-but-unpopped; None once popped or
        failed. Read-only view for fronting layers (the gateway polls
        ``.tokens`` off it for streaming delivery)."""
        for req in self._pending:
            if req.rid == rid:
                return req
        for req in self._slot_req.values():
            if req.rid == rid:
                return req
        adm = getattr(self, "_admitting", None)
        if adm is not None and adm["req"].rid == rid:
            return adm["req"]
        return self._finished.get(rid)

    def failure(self, rid: int) -> Optional[Exception]:
        """The stored typed failure for ``rid`` (``DeadlineExceeded``,
        …) without raising/popping it; None while healthy."""
        return self._failed.get(rid)

    def abort(self, rid: int) -> bool:
        """Withdraw a LIVE request without recording a failure — the
        caller re-owns it (the gateway's drain-requeue path moves the
        request to a survivor and resumes token-exact from
        ``prompt ⧺ delivered``). Pending requests leave the queue;
        active ones release their slot (and cache rows); a mid-admission
        paged request releases its pages, same mechanics as deadline
        expiry. Returns True when something was withdrawn; False for an
        unknown rid or a terminal request (finished results stay
        poppable, failures stay raised by ``pop_result``)."""
        with self._intake:
            for req in list(self._pending):
                if req.rid == rid:
                    self._pending.remove(req)
                    return True
        for slot, req in list(self._slot_req.items()):
            if req.rid == rid:
                del self._slot_req[slot]
                self._release_slot(slot)
                req.slot = None
                return True
        adm = getattr(self, "_admitting", None)
        if adm is not None and adm["req"].rid == rid:
            self._release_row(adm["row"])
            self._free_slots.append(adm["slot"])
            self._admitting = None
            return True
        return False


class ContinuousBatcher(_BatcherBase):
    """Continuous batcher over a causal LM's dense KV cache.

    model: a GPT2ForCausalLM or LlamaForCausalLM (eval mode — any model
    exposing prefill/decode_step with the [B, 1] t convention). max_batch: slot count (ONE
    compiled decode executable serves every step at this batch). s_max:
    per-slot cache rows (prompt + generation must fit). eos_id: optional
    early-stop token. compile: jit.to_static the decode step (recommended;
    disable for debugging).
    """

    _engine = "dense"

    def __init__(self, model, max_batch: int = 8, s_max: int = 256,
                 eos_id: Optional[int] = None, compile: bool = True,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: Optional[float] = None,
                 seed: Optional[int] = None,
                 max_queue_depth: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 prompt_buckets="pow2"):
        import paddle_tpu as paddle

        self.model = model
        self._do_sample = do_sample
        self._temperature = temperature
        self._top_k = top_k
        self._top_p = top_p
        self._rng = np.random.RandomState(seed)
        self.max_batch = max_batch
        self.s_max = s_max
        self.eos_id = eos_id
        cfg = model.config
        self._check_window(cfg, s_max)
        L, d = cfg.num_hidden_layers, cfg.head_dim
        # GQA models cache at kv-head count (unexpanded)
        kvh = getattr(cfg, "num_key_value_heads", None) \
            or cfg.num_attention_heads
        self._caches = paddle.zeros([L, 2, max_batch, kvh, s_max, d],
                                    dtype=cfg.dtype)
        self._t = np.full((max_batch, 1), s_max - 1, np.int32)  # parked
        self._free = list(range(max_batch))
        self._init_queues(max_queue_depth=max_queue_depth,
                          default_deadline_s=default_deadline_s)
        self._last_tok = np.zeros((max_batch, 1), np.int64)
        # Admission pads prompts up this ladder (perf.buckets spec; None
        # disables): O(#buckets) prefill signatures instead of one per
        # distinct prompt length. Capped at s_max so the top rung is
        # always admissible.
        from ..perf.buckets import resolve_ladder
        self._prompt_ladder = resolve_ladder(prompt_buckets, hi=s_max)
        if compile:
            from .. import jit
            # donate the caches argument (tensor arg index 1): XLA reuses
            # the cache HBM in place instead of double-buffering per step
            self._step_fn = jit.to_static(model.decode_step,
                                          donate_args=(1,))
            self._prefill_fn = jit.to_static(model.prefill)
            # opprof observatory identities for the serving executables
            # (only meaningful on the compiled path)
            self._step_fn._opprof_label = "serving.decode"
            self._prefill_fn._opprof_label = "serving.prefill"
        else:
            self._step_fn = model.decode_step
            self._prefill_fn = model.prefill

    # -- request lifecycle --------------------------------------------------
    def _release_slot(self, slot: int):
        self._free.append(slot)
        self._t[slot, 0] = self.s_max - 1  # park

    def _admit(self) -> List[int]:
        """Move pending requests into free slots (prefill writes the slot's
        cache rows). Prompts are right-padded up the shared bucket ladder
        (``prompt_buckets``), so steady state runs O(#buckets) prefill
        signatures instead of one per distinct prompt length; the model
        gathers the true last-token logits at ``n_valid - 1``. Padded
        tokens are counted in ``serving.bucket_pad_waste``. Returns rids
        that finished AT admission (max_new_tokens == 1 or EOS on the
        prefill token)."""
        import paddle_tpu as paddle
        finished = []
        while True:
            with self._intake:
                if not (self._pending and self._free):
                    break
                req = self._pending.pop(0)
            slot = self._free.pop(0)
            self._trace_admit_begin(req)
            prompt = req.prompt
            n = len(prompt)
            if self._prompt_ladder is not None:
                bucket = self._prompt_ladder.bucket(n)
                if bucket != n:
                    # labeled by resolved rung so telemetry_dump can
                    # attribute waste per bucket without re-deriving the
                    # ladder
                    from ..observability.metrics import get_registry
                    get_registry().counter(
                        "serving.bucket_pad_waste",
                        "pad tokens admission added to reach the prompt "
                        "bucket",
                        labelnames=("rung",)).labels(
                            rung=str(bucket)).inc(bucket - n)
                    prompt = np.concatenate(
                        [prompt, np.zeros(bucket - n, prompt.dtype)])
                n_valid = paddle.to_tensor(np.full((1, 1), n, np.int32))
            else:
                n_valid = None
            ids = paddle.to_tensor(prompt[None, :])
            self._trace_prefill_begin(req)
            with paddle.no_grad():
                if n_valid is not None:
                    # n_valid is passed even for exact-rung prompts so every
                    # admission in a bucket shares ONE prefill signature
                    logits, cache, _t = self._prefill_fn(
                        ids, self.s_max, n_valid)
                else:
                    logits, cache, _t = self._prefill_fn(ids, self.s_max)
            self._trace_prefill_end(req, prompt_tokens=n,
                                    padded_to=len(prompt))
            # write the slot: caches[:, :, slot] = cache[:, :, 0]
            self._caches[:, :, slot] = cache[:, :, 0]
            tok = int(self._pick(np.asarray(logits._data)[:, -1])[0])
            req.slot = slot
            req.tokens.append(tok)
            self._tele.on_admit()
            self._tele.on_token(req)
            self._slot_req[slot] = req
            self._t[slot, 0] = len(req.prompt)
            self._last_tok[slot, 0] = tok
            self._trace_admit_end(req, slot)
            if self._maybe_finish(req, tok):
                finished.append(req.rid)
        return finished

    # -- the engine ---------------------------------------------------------
    def _step_impl(self) -> List[int]:
        """Admit, decode one token for every active slot, evict finished.
        Returns the rids that finished during THIS call (including ones
        that finished at admission)."""
        import paddle_tpu as paddle
        finished = self._admit()
        self._tele.set_gauges(len(self._pending), len(self._slot_req))
        if not self._slot_req:
            return finished
        self._tele.on_step()
        self._tele.on_occupancy(len(self._slot_req))
        n_active = len(self._slot_req)
        t0 = _time.perf_counter()
        tok_t = paddle.to_tensor(self._last_tok)
        t_t = paddle.to_tensor(self._t)
        # serving is inference by construction: the batcher supplies the
        # no_grad scope its donating compiled step requires
        with paddle.no_grad():
            logits, self._caches, _ = self._step_fn(tok_t, self._caches,
                                                    t_t)
        next_tok = self._pick(np.asarray(logits._data)[:, -1])
        for slot, req in list(self._slot_req.items()):
            tok = int(next_tok[slot])
            self._t[slot, 0] += 1
            req.tokens.append(tok)
            self._tele.on_token(req)
            self._last_tok[slot, 0] = tok
            if self._maybe_finish(req, tok):
                finished.append(req.rid)
        self._tele.on_decode_time(_time.perf_counter() - t0,
                                  tokens=n_active)
        self._tele.set_gauges(len(self._pending), len(self._slot_req))
        return finished


class PagedContinuousBatcher(_BatcherBase):
    """Continuous batching over the PAGED (block) KV cache.

    Reference surface: the vLLM-style serving loop the reference builds
    around block_multihead_attention
    (incubate/nn/functional/block_multihead_attention.py:19) — cache
    memory is a pool of physical pages, a block table maps each live
    sequence's logical blocks onto pool rows, and the scheduler admits/
    preempts by moving pages, not tensors.

    TPU design: the pool `[n_pages+1, H, bs, D]` per layer and the block
    table `[max_batch, blocks_per_seq]` both have static shapes, so ONE
    compiled decode executable serves every step at every occupancy. The
    host owns the free list; parked slots point every logical block at a
    reserved SCRATCH page (pool row n_pages) with dec_len 0, so their
    garbage decode writes land in scratch and never touch a live page.

    decode_block=K (greedy only): pure-decode phases run K steps as ONE
    compiled executable with on-device argmax feedback — one dispatch
    and one K*B-token download per K tokens instead of K dispatches
    each hauling [B, V] logits to the host. On a remote-relayed device
    the per-dispatch latency dominates a small model's decode compute,
    so this is the serving-throughput lever there. Token-exact vs the
    per-step path; EOS/budget overshoot inside a block is discarded on
    the host and its K/V rows land in the slot's own pages or scratch.

    policy:
      * ``"reserve"`` — admission reserves the worst-case page count
        (ceil((prompt+max_new)/bs)) up front; head-of-line blocks when
        the pool can't cover it. Deterministic, no preemption.
      * ``"ondemand"`` — admission reserves only the prompt's pages;
        growth allocates one page as a sequence crosses each block
        boundary. On pool exhaustion the most-recently admitted request
        is PREEMPTED: its pages return to the pool and it re-queues with
        prompt ⧺ generated-so-far, so a later re-prefill recomputes its
        state exactly (greedy decode reproduces the same continuation).
    """

    _engine = "paged"

    def __init__(self, model, max_batch: int = 8, s_max: int = 256,
                 block_size: int = 16, n_pages: Optional[int] = None,
                 eos_id: Optional[int] = None, compile: bool = True,
                 policy: str = "reserve",
                 prefill_chunk: Optional[int] = None,
                 cache_quant: Optional[str] = None,
                 kv_quant: Optional[str] = None,
                 tier_quant: Optional[str] = None,
                 fused_admission: bool = False,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: Optional[float] = None,
                 seed: Optional[int] = None,
                 decode_block: Optional[int] = None,
                 max_queue_depth: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 prefix_cache: bool = False,
                 host_kv_gib: Optional[float] = None,
                 disk_kv_dir: Optional[str] = None,
                 disk_kv_gib: Optional[float] = None,
                 promo_timeout_s: float = 5.0,
                 promo_slots: int = 2,
                 promo_chunk_blocks: Optional[int] = 4,
                 session_store=None,
                 prompt_buckets=None,
                 draft_model=None, draft_k: int = 4):
        import paddle_tpu as paddle

        if policy not in ("reserve", "ondemand"):
            raise ValueError(f"unknown policy {policy!r}")
        if promo_slots < 1:
            raise ValueError("promo_slots must be >= 1")
        if promo_chunk_blocks is not None and promo_chunk_blocks < 1:
            raise ValueError("promo_chunk_blocks must be >= 1 (or None "
                             "for one whole-tail chunk)")
        if prefix_cache and cache_quant:
            raise ValueError(
                "prefix_cache shares pages across requests; dynamic "
                "cachekv quant scales are per-request, so a shared page "
                "would replay with the wrong scales — use static "
                "calibration or disable one")
        if prefix_cache and fused_admission:
            raise ValueError(
                "prefix_cache is not supported with fused_admission "
                "(the fused chunk streams the FULL prompt at a fixed "
                "offset grid; a cached-prefix suffix start would need a "
                "second executable per offset)")
        if draft_model is not None:
            if do_sample:
                raise ValueError("speculative decoding is greedy-only "
                                 "(draft_model requires do_sample=False)")
            if decode_block:
                raise ValueError("draft_model and decode_block are both "
                                 "decode-dispatch amortizers; pick one")
            if fused_admission:
                raise ValueError("draft_model is not supported with "
                                 "fused_admission")
            if cache_quant:
                raise ValueError("draft_model is not supported with "
                                 "dynamic cachekv quant")
            if prefill_chunk:
                raise ValueError("draft_model is not supported with "
                                 "prefill_chunk (the draft pool would "
                                 "need its own chunk executables)")
            if draft_k < 1:
                raise ValueError("draft_k must be >= 1")
            if draft_model.config.vocab_size != model.config.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_model.config.vocab_size} != "
                    f"target vocab {model.config.vocab_size}")
        if decode_block is not None:
            if decode_block < 2:
                raise ValueError("decode_block must be >= 2 (1 is the "
                                 "plain per-step path)")
            if do_sample:
                # the in-block feedback is an on-device argmax; sampled
                # selection stays on the host path
                raise ValueError("decode_block requires greedy decoding "
                                 "(do_sample=False)")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if cache_quant not in (None, "dynamic_int8"):
            raise ValueError(f"unknown cache_quant {cache_quant!r} "
                             f"(use None or 'dynamic_int8'; static int8 "
                             f"comes from model.calibrate_cachekv_int8)")
        if kv_quant not in (None, "int8"):
            raise ValueError(f"unknown kv_quant {kv_quant!r} "
                             f"(use None or 'int8')")
        if kv_quant:
            # the explicit contract layer over the static-calibration
            # path: pages store int8 + the model's calibrated per-head
            # scales, dequantized inline at attention time (XLA fuses
            # the dequant into the matmul)
            if cache_quant:
                raise ValueError(
                    "kv_quant='int8' (static calibrated pages) and "
                    "cache_quant (dynamic per-request scales) are two "
                    "quantizers for the same pool; pick one")
            if getattr(model, "_cachekv_scales", None) is None:
                raise ValueError(
                    "kv_quant='int8' needs static per-head cache scales: "
                    "run model.calibrate_cachekv_int8(sample_ids) before "
                    "constructing the batcher")
            if draft_model is not None:
                raise ValueError(
                    "kv_quant is not supported with draft_model (the "
                    "draft pool would need its own calibration pass)")
        if tier_quant not in (None, "int8"):
            raise ValueError(f"unknown tier_quant {tier_quant!r} "
                             f"(use None or 'int8')")
        if tier_quant:
            if not prefix_cache:
                raise ValueError(
                    "tier_quant quantizes demoted host/disk tier blobs — "
                    "it needs prefix_cache=True (with a host tier)")
            if getattr(model, "_cachekv_scales", None) is not None:
                raise ValueError(
                    "tier_quant is redundant with calibrated int8 pages: "
                    "an int8 pool already spills int8 blobs natively "
                    "(and re-quantizing int8 codes would lose bits)")
        if cache_quant and prefill_chunk == 1:
            # a 1-token first chunk is decode-shaped (enc == 0,
            # this == 1): the op's scale opt-in guard rejects it, so fail
            # at construction instead of at first admission
            raise ValueError("cache_quant='dynamic_int8' needs "
                             "prefill_chunk >= 2 (a 1-token chunk is "
                             "indistinguishable from a decode step)")
        if fused_admission and not prefill_chunk:
            raise ValueError("fused_admission needs prefill_chunk (the "
                             "chunk width of the fused executable)")
        if fused_admission and cache_quant:
            raise ValueError("fused_admission + dynamic cachekv quant is "
                             "not supported; use static calibration")
        if prefill_chunk is not None and prefill_chunk > s_max:
            raise ValueError(f"prefill_chunk={prefill_chunk} exceeds "
                             f"s_max={s_max}")
        if fused_admission:
            cap = -(-s_max // block_size) * block_size
            if cap % prefill_chunk:
                # the fused chunk is FIXED-width; a capacity-clamped tail
                # would re-pad past the block table and (via jnp's index
                # clamping) overwrite the sequence's real last page
                raise ValueError(
                    f"fused_admission needs the slot capacity ({cap}) to "
                    f"be a multiple of prefill_chunk ({prefill_chunk})")
        cfg = model.config
        self._check_window(cfg, s_max)
        self.model = model
        self.max_batch = max_batch
        self.s_max = s_max
        self.block_size = block_size
        self.blocks_per_seq = -(-s_max // block_size)
        if n_pages is None:
            n_pages = max_batch * self.blocks_per_seq
        self.n_pages = n_pages
        self.eos_id = eos_id
        self.policy = policy
        self._do_sample = do_sample
        self._temperature = temperature
        self._top_k = top_k
        self._top_p = top_p
        self._rng = np.random.RandomState(seed)

        self._scratch = n_pages                     # reserved pool row
        self._free_pages = list(range(n_pages))
        self._bt = np.full((max_batch, self.blocks_per_seq), self._scratch,
                           np.int32)
        self._dec = np.zeros((max_batch,), np.int32)
        self._free_slots = list(range(max_batch))
        self._init_queues(max_queue_depth=max_queue_depth,
                          default_deadline_s=default_deadline_s)
        self._admit_order: List[int] = []           # slots, oldest first
        self._last_tok = np.zeros((max_batch,), np.int64)

        # cross-request radix prefix reuse (SGLang RadixAttention shape):
        # admission matches the longest cached FULL-block prefix, points
        # the slot's block-table front at the cached pages, and prefills
        # only the suffix; the tree pins pages under live slots and
        # LRU-evicts unpinned chains back into the free list on pressure
        self.prefix_cache = None
        self._slot_nodes: Dict[int, list] = {}
        # tiered KV: one in-flight promotion STREAM (FIFO head only — the
        # batcher is single-threaded, so only the head request can wait),
        # pipelined as a bounded multi-chunk queue through the async
        # device_put worker: up to ``promo_slots`` chunks of
        # ``promo_chunk_blocks`` blocks are in flight at once, completed
        # chunks install in order at step boundaries while later chunks
        # (and decode) keep running. ``_promo_denied`` is an rid denylist
        # for requests whose promotion already failed (they fall back to
        # full prefill, never retry).
        self._promo = None
        self._promo_denied: set = set()
        self._promoter = None
        self.promo_timeout_s = promo_timeout_s
        self.promo_slots = promo_slots
        self.promo_chunk_blocks = promo_chunk_blocks
        self._demoted_seen = 0      # cache.demoted_bytes already countered
        # durable sessions: session id -> session-pinned node chain (spin
        # refs survive demotion; see prefix_cache.session_pin) and the
        # shared manifest store that makes a pause resumable on ANY
        # replica
        from .session_store import SessionStore
        self.session_store = (SessionStore(session_store)
                              if isinstance(session_store, str)
                              else session_store)
        self._session_pins: Dict[str, list] = {}
        if prefix_cache:
            from .prefix_cache import RadixPrefixCache, HostTier, DiskTier
            host_gib = (host_kv_gib if host_kv_gib is not None else
                        float(_os.environ.get("PADDLE_KV_HOST_GIB", "0")
                              or 0.0))
            host_tier = None
            if host_gib > 0:
                ddir = disk_kv_dir or _os.environ.get("PADDLE_KV_DISK_DIR")
                nxt = None
                if ddir:
                    dgib = (disk_kv_gib if disk_kv_gib is not None else
                            float(_os.environ.get("PADDLE_KV_DISK_GIB",
                                                  "16") or 16.0))
                    nxt = DiskTier(ddir, int(dgib * (1 << 30)))
                host_tier = HostTier(int(host_gib * (1 << 30)),
                                     next_tier=nxt)
            self.prefix_cache = RadixPrefixCache(
                block_size, host_tier=host_tier,
                spill=self._read_page_blob if host_tier is not None
                else None)
            if host_tier is not None:
                from ..perf.prefetch import AsyncLoader
                self._promoter = AsyncLoader(
                    depth=max(2, promo_slots),
                    name="paddle_tpu_kv_promoter",
                    workers=max(1, promo_slots))
        # optional admission ladder: the suffix prefill pads up shared
        # rungs (O(#buckets) prefill signatures, same lever as the dense
        # batcher's prompt_buckets); None keeps exact-length prefill
        from ..perf.buckets import resolve_ladder
        self._prompt_ladder = resolve_ladder(prompt_buckets, hi=s_max)
        from ..observability.metrics import get_registry as _get_reg
        _reg = _get_reg()
        self._prefix_hit_c = _reg.counter(
            "serving.prefix_hit_tokens",
            "prompt tokens served from the radix prefix cache")
        self._prefix_miss_c = _reg.counter(
            "serving.prefix_miss_tokens",
            "prompt tokens actually prefilled (no cached prefix)")
        self._prefix_evict_c = _reg.counter(
            "serving.prefix_evictions",
            "prefix-cache pages LRU-evicted under page pressure")
        self._pages_leaked_g = _reg.gauge(
            "serving.pages_leaked",
            "pages unaccounted for by free-list + block tables + prefix "
            "cache (an OOM-much-later bug if ever nonzero)")
        self._tier_hit_c = _reg.counter(
            "serving.prefix_tier_hit_tokens",
            "cached prompt tokens served, by the tier they were resident "
            "in at match time", labelnames=("tier",))
        self._promote_h = _reg.histogram(
            "serving.prefix_promotion_seconds",
            "host->device prefix promotion latency (submit to install)")
        self._promo_c = _reg.counter(
            "serving.prefix_promotions",
            "prefix pages promoted host/disk -> device")
        self._promo_fail_c = _reg.counter(
            "serving.prefix_promotion_failures",
            "promotions that failed/timed out/lost the page race "
            "(admission degraded to full prefill)")
        self._demote_bytes_c = _reg.counter(
            "serving.prefix_demoted_bytes",
            "KV bytes spilled device -> host tier on eviction")
        self._host_bytes_g = _reg.gauge(
            "serving.kv_host_bytes",
            "bytes currently held by the host KV tier")
        self._kv_quant_g = _reg.gauge(
            "serving.kv_quant_enabled",
            "1 when the paged KV pool stores int8 pages (static "
            "calibrated scales), else 0")
        self._kv_quant_saved_g = _reg.gauge(
            "serving.kv_quant_bytes_saved",
            "pool bytes saved by int8 KV pages vs the model fp dtype")
        self._spill_raw_c = _reg.counter(
            "serving.prefix_spill_raw_bytes",
            "pre-quantization KV bytes demoted to the host tier "
            "(what the spill WOULD cost stored raw)")
        self._spill_blob_c = _reg.counter(
            "serving.prefix_spill_blob_bytes",
            "as-stored KV bytes demoted to the host tier (int8+scales "
            "when tier_quant is on; equals raw otherwise)")
        self._dequant_h = _reg.histogram(
            "quant.dequant_seconds",
            "main-thread blob dequantize time when installing promoted "
            "tier chunks (the overhead tier_quant pays on promotion)")

        self.cache_quant = cache_quant
        self.kv_quant = kv_quant
        self.tier_quant = tier_quant
        pool = model.paged_alloc(
            n_pages + 1, block_size,
            cache_dtype="int8" if cache_quant else None)
        # paged_alloc auto-allocates int8 pages whenever the model
        # carries calibrated static scales — kv_quant='int8' is the
        # explicit contract (validated above), but the gauge reflects
        # the pool as actually allocated either way
        pool_int8 = bool(cache_quant) or (
            getattr(model, "_cachekv_scales", None) is not None)
        self._kv_quant_g.set(1 if pool_int8 else 0)
        if pool_int8:
            elems = sum(int(np.prod(kc.shape)) + int(np.prod(vc.shape))
                        for kc, vc in pool)
            try:
                fp_itemsize = np.dtype(
                    getattr(cfg, "dtype", "float32") or "float32").itemsize
            except TypeError:   # bfloat16-family names numpy can't parse
                fp_itemsize = 2
            self._kv_quant_saved_g.set(elems * max(0, fp_itemsize - 1))
        self._state = {
            "layers": pool,
            "block_tables": paddle.to_tensor(self._bt),
            "dec_lens": paddle.to_tensor(self._dec),
            "block_size": block_size,
            "capacity": self.blocks_per_seq * block_size,
            "zeros_b": paddle.to_tensor(np.zeros((max_batch,), np.int32)),
            "ones_b": paddle.to_tensor(np.ones((max_batch,), np.int32)),
            "cu_b": paddle.to_tensor(np.arange(max_batch + 1,
                                               dtype=np.int32)),
        }
        if cache_quant:
            # per-(slot, kv-head) dynamic scales, host-owned like the
            # block table; each sequence's prefill fills its slot row
            cfg = model.config
            kvh = getattr(cfg, "num_key_value_heads", None) \
                or cfg.num_attention_heads
            self._scales_np = [
                {k: np.ones((max_batch, kvh), np.float32)
                 for k in ("kq", "vq", "kdq", "vdq")}
                for _ in range(cfg.num_hidden_layers)]
            self._state["cache_scales"] = None  # filled by _sync_tables
            self._scales_dirty = True

        # in-batcher speculative decoding (the _speculative_loop recipe,
        # batched): the DRAFT pool mirrors the target pool's geometry and
        # SHARES self._bt, so one block table names both models' pages.
        # Per round: batched draft catch-up append (ends at each slot's
        # pending token -> proposal 1), k-1 draft decode steps, then ONE
        # target verify pass scoring pending + all k proposals; accept
        # the longest matching prefix + the target's correction. Output
        # is the target's greedy sequence token for token — the draft
        # only ever changes HOW MANY tokens a dispatch yields.
        self.draft_model = draft_model
        self.draft_k = draft_k
        self.spec_stats = {"rounds": 0, "proposed": 0, "matched": 0,
                           "fallback_steps": 0}
        if draft_model is not None:
            self._check_window(draft_model.config, s_max)
            dpool = draft_model.paged_alloc(n_pages + 1, block_size)
            self._ddec = np.zeros((max_batch,), np.int32)
            self._dstate = {
                "layers": dpool,
                "block_tables": paddle.to_tensor(self._bt),
                "dec_lens": paddle.to_tensor(self._ddec),
                "block_size": block_size,
                "capacity": self.blocks_per_seq * block_size,
                "zeros_b": self._state["zeros_b"],
                "ones_b": self._state["ones_b"],
                "cu_b": self._state["cu_b"],
            }

            def _verify_body(ids, layers, bt, dec):
                return model.paged_prefill_into(
                    ids, layers, bt, block_size, dec_base=dec,
                    logits_all=True)

            def _catchup_body(ids, layers, bt, dec, at):
                return draft_model.paged_prefill_into(
                    ids, layers, bt, block_size, dec_base=dec,
                    logits_at=at)
            if compile:
                from .. import jit
                self._dstep_fn = jit.to_static(
                    draft_model.paged_decode_step, donate_args=(1,))
                self._verify_fn = jit.to_static(_verify_body,
                                                donate_args=(1,))
                self._catchup_fn = jit.to_static(_catchup_body,
                                                 donate_args=(1,))
                self._dstep_fn._opprof_label = "serving.draft_decode"
                self._verify_fn._opprof_label = "serving.verify"
                self._catchup_fn._opprof_label = "serving.catchup"
            else:
                self._dstep_fn = draft_model.paged_decode_step
                self._verify_fn = _verify_body
                self._catchup_fn = _catchup_body
            # catch-up width varies per round (1-2 steady state, wide
            # after fallback rounds); pad it up a pow2 ladder so the
            # catch-up executable count stays O(log s_max)
            from ..perf.buckets import BucketLadder
            self._cu_ladder = BucketLadder.pow2(hi=s_max)
        self.prefill_chunk = prefill_chunk
        self.fused_admission = fused_admission
        self._admitting: Optional[dict] = None
        if fused_admission:
            # idle chunk inputs are byte-identical every step: build once
            self._idle_chunk = (
                paddle.to_tensor(np.zeros((prefill_chunk,), np.int64)),
                paddle.to_tensor(np.full((1, self.blocks_per_seq),
                                         self._scratch, np.int32)),
                paddle.to_tensor(np.array([0], np.int32)),
                paddle.to_tensor(np.array([0], np.int32)))
        if fused_admission:
            if compile:
                from .. import jit
                self._fused_fn = jit.to_static(model.paged_fused_step,
                                               donate_args=(5,))
                self._fused_fn._opprof_label = "serving.fused"
            else:
                self._fused_fn = model.paged_fused_step
        if compile:
            from .. import jit
            # donate the state pytree (arg 1): the page pool is the big
            # buffer — XLA appends into it in place every step
            self._step_fn = jit.to_static(model.paged_decode_step,
                                          donate_args=(1,))
            self._step_fn._opprof_label = "serving.paged_decode"
        else:
            self._step_fn = model.paged_decode_step
        self.decode_block = decode_block
        if decode_block:
            # K decode steps unrolled into ONE executable with on-device
            # greedy feedback: one dispatch (and one host round trip for
            # K*B token ids instead of K full [B, V] logits downloads)
            # per K tokens. Through a remote-relay device the per-call
            # latency dominates the decode step's compute, so this is
            # the serving-throughput lever for pure-decode phases.
            def _block_body(tok, state, _K=decode_block, _m=model):
                toks = []
                for _ in range(_K):
                    logits, state = _m.paged_decode_step(tok, state)
                    tok = paddle.argmax(logits, axis=-1)
                    toks.append(tok)
                return paddle.stack(toks), state          # [K, B]
            if compile:
                from .. import jit
                self._block_fn = jit.to_static(_block_body,
                                               donate_args=(1,))
                self._block_fn._opprof_label = "serving.decode_block"
            else:
                self._block_fn = _block_body
        if prefill_chunk is not None:
            # one fixed-width append executable serves EVERY prompt
            # length (vLLM chunked prefill); without it each distinct
            # prompt length costs a fresh prefill compile
            def _chunk(ids, layers, bt_row, dec, at):
                return model.paged_prefill_into(
                    ids, layers, bt_row, block_size, dec_base=dec,
                    logits_at=at)
            if compile:
                from .. import jit
                # donate the pool (arg 1) exactly like the decode step —
                # chunked prefill must not double-buffer the cache HBM
                self._chunk_fn = jit.to_static(_chunk, donate_args=(1,))
                self._chunk_fn._opprof_label = "serving.paged_prefill_chunk"
            else:
                self._chunk_fn = _chunk
            if cache_quant:
                # dynamic cachekv-int8 x chunked prefill: TWO fixed-width
                # executables — the first chunk computes the sequence's
                # scales (pad tail masked out of the stats via nvalid)
                # and returns them; later chunks consume them, so every
                # row of the timeline quantizes with ONE consistent
                # scale set (VERDICT r3 #5; reference analog
                # block_multihead_attention.py's scales+chunk signature)
                def _chunk_dyn_first(ids, layers, bt_row, dec, at, nvalid):
                    return model.paged_prefill_into(
                        ids, layers, bt_row, block_size, dec_base=dec,
                        logits_at=at, dynamic_cache_scales=True,
                        dynamic_scale_valid=nvalid)

                def _chunk_dyn_rest(ids, layers, bt_row, dec, at, scales):
                    return model.paged_prefill_into(
                        ids, layers, bt_row, block_size, dec_base=dec,
                        logits_at=at, cache_scales=scales)
                if compile:
                    self._chunk_dyn_first_fn = jit.to_static(
                        _chunk_dyn_first, donate_args=(1,))
                    self._chunk_dyn_rest_fn = jit.to_static(
                        _chunk_dyn_rest, donate_args=(1,))
                    self._chunk_dyn_first_fn._opprof_label = \
                        "serving.prefill_chunk_scales"
                    self._chunk_dyn_rest_fn._opprof_label = \
                        "serving.prefill_chunk_quant"
                else:
                    self._chunk_dyn_first_fn = _chunk_dyn_first
                    self._chunk_dyn_rest_fn = _chunk_dyn_rest

    # -- page accounting ----------------------------------------------------
    def _pages_for(self, n_rows: int) -> int:
        return -(-n_rows // self.block_size)

    def _alloc_pages_row(self, row: np.ndarray, upto_row: int) -> bool:
        """Grow a block-table row (a view into self._bt or a detached
        admission row) so rows [0, upto_row) are backed. A dry free list
        LRU-evicts unpinned prefix-cache chains first (cached-but-idle
        pages are reclaimable capacity, not occupancy). Returns False
        (allocating nothing) if even that can't cover it."""
        need_blocks = self._pages_for(upto_row)
        have = int(np.sum(row != self._scratch))
        grow = need_blocks - have
        if grow <= 0:
            return True
        if grow > len(self._free_pages) and self.prefix_cache is not None:
            self._evict_cache_pages(grow - len(self._free_pages))
        if grow > len(self._free_pages):
            return False
        for b in range(have, need_blocks):
            row[b] = self._free_pages.pop()
        return True

    def _alloc_pages(self, slot: int, upto_row: int) -> bool:
        return self._alloc_pages_row(self._bt[slot], upto_row)

    def _available_pages(self) -> int:
        """Pages an allocation could obtain right now: the free list plus
        whatever the prefix cache would surrender to eviction."""
        n = len(self._free_pages)
        if self.prefix_cache is not None:
            n += self.prefix_cache.evictable_pages()
        return n

    # -- tiered KV: demotion + async promotion ------------------------------
    def _evict_cache_pages(self, n: int) -> List[int]:
        """Reclaim up to n pages from the prefix cache (demoting to the
        host tier when one is attached), mirroring the demoted-byte
        delta into the counter."""
        freed = self.prefix_cache.evict(n)
        if freed:
            self._free_pages.extend(freed)
            self._prefix_evict_c.inc(len(freed))
        d = self.prefix_cache.demoted_bytes - self._demoted_seen
        if d:
            self._demote_bytes_c.inc(d)
            self._demoted_seen = self.prefix_cache.demoted_bytes
        return freed

    @staticmethod
    def _quant_page(arr):
        """Per-head symmetric int8 quantization of one KV page row
        [H, block, D]: returns (int8 codes, float32 dequant scale
        [H, 1, 1]). amax==0 heads keep scale 1.0 so all-zero padding
        round-trips exactly."""
        a = np.asarray(arr, np.float32)
        amax = np.abs(a).max(axis=(1, 2), keepdims=True)
        scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
        q = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
        return q, scale

    def _quant_rows(self, rows):
        """Quantize a list of per-layer (k_page, v_page) rows into the
        tier-blob twin lists (int8 pages, per-head scales)."""
        pages, scales = [], []
        for k, v in rows:
            k8, ks = self._quant_page(k)
            v8, vs = self._quant_page(v)
            pages.append((k8, v8))
            scales.append((ks, vs))
        return pages, scales

    @staticmethod
    def _dequant_rows(pages, scales):
        return [(k8.astype(np.float32) * ks, v8.astype(np.float32) * vs)
                for (k8, v8), (ks, vs) in zip(pages, scales)]

    def _read_page_blob(self, node):
        """The cache's spill callback: read one node's KV rows off the
        pool back to pinned host numpy (on the CPU proxy this is a plain
        copy; on TPU the same call is the D2H readback). The draft pool
        shares the block table, so its rows spill alongside — promotion
        must restore BOTH pools for the page to be reusable.

        With ``tier_quant='int8'`` the fp rows demote as int8 codes plus
        per-head scales (the ``q`` tag marks the blob; ``_install_chunk``
        dequantizes on promotion), roughly halving what a chain costs the
        host/disk byte budget. An int8 pool (static calibration) never
        takes this path — its pages spill int8 natively and reinstall
        verbatim."""
        from .prefix_cache import blob_nbytes
        page = int(node.page)
        rows = [(np.asarray(kc._data[page]).copy(),
                 np.asarray(vc._data[page]).copy())
                for kc, vc in self._state["layers"]]
        drows = None
        if self.draft_model is not None:
            drows = [(np.asarray(kc._data[page]).copy(),
                      np.asarray(vc._data[page]).copy())
                     for kc, vc in self._dstate["layers"]]
        raw = blob_nbytes(rows) + (blob_nbytes(drows) if drows else 0)
        if self.tier_quant:
            # the "ts"/"ds" scale keys ARE the quantized-blob tag (a
            # string marker would poison the promotion device_put — the
            # loader ships the whole pytree and every leaf must be a
            # JAX-typable array)
            pages, scales = self._quant_rows(rows)
            blob = {"t": pages, "ts": scales}
            if drows is not None:
                dpages, dscales = self._quant_rows(drows)
                blob["d"] = dpages
                blob["ds"] = dscales
        else:
            blob = {"t": rows}
            if drows is not None:
                blob["d"] = drows
        self._spill_raw_c.inc(raw)
        self._spill_blob_c.inc(blob_nbytes(blob))
        return blob

    def _submit_promo_chunk(self, promo) -> bool:
        """Move one waiting chunk into flight. Its blobs are read off
        their tier IN THE WORKER (a callable payload — the loader
        materializes it before the device_put), so a later chunk's
        host/disk reads overlap an earlier chunk's main-thread install
        and, on a real accelerator, the in-flight DMA. Safe because
        every stream node carries ``node.promo`` and a pin for the
        duration: the evictors skip it, so its tier blob cannot move
        under the worker. A read error fails the chunk's future and the
        poller cancels the stream; False here only means the submit
        itself failed (loader closed/draining)."""
        from .prefix_cache import blob_nbytes
        chunk = promo["waiting"].pop(0)
        nodes = list(chunk["nodes"])
        cache = self.prefix_cache

        def _read():
            blobs = [cache.node_blob(n) for n in nodes]
            # worker-side write, published to the main thread by the
            # future's Event — read only after done()
            chunk["nbytes"] = [blob_nbytes(b) for b in blobs]
            return blobs

        try:
            chunk["future"] = self._promoter.submit(_read)
        except Exception:
            promo["waiting"].insert(0, chunk)
            return False
        promo["chunks"].append(chunk)
        return True

    def _start_promotion(self, req, dev: list, tail: list) -> bool:
        """Open a pipelined promotion stream for the off-device tail of
        ``req``'s matched path. Pins the WHOLE path (device prefix too:
        eviction must not demote what the request is about to use) and
        reserves one target page per tail node up front, so a completed
        transfer always has somewhere to land. The tail is split into
        ``promo_chunk_blocks``-block chunks with up to ``promo_slots``
        in flight through the async worker at once; ``promo_slots=1``
        with ``promo_chunk_blocks=None`` reproduces the old serial
        single-slot behavior. False (nothing pinned, nothing reserved)
        if pages can't be found or chaos says no — the caller degrades
        to device-prefix-only prefill."""
        from ..resilience.chaos import fault_point
        try:
            fault_point("kv.host_promote")
        except Exception:
            self._promo_fail_c.inc()
            self.prefix_cache.promotion_failures += 1
            self._promo_denied.add(req.rid)
            return False
        pinned = dev + tail
        self.prefix_cache.pin(pinned)
        need = len(tail)
        if need > len(self._free_pages):
            self._evict_cache_pages(need - len(self._free_pages))
        if need > len(self._free_pages):
            self.prefix_cache.unpin(pinned)
            return False
        pages = [self._free_pages.pop() for _ in range(need)]
        csize = self.promo_chunk_blocks or len(tail)
        t0 = _time.perf_counter()
        promo = {"req": req, "pinned": pinned,
                 # nodes/pages below shrink as chunks install — they are
                 # the NOT-YET-INSTALLED remainder (audit + cancel view)
                 "nodes": list(tail), "pages": list(pages),
                 "chunks": [],    # in flight, FIFO
                 "waiting": [{"nodes": tail[i:i + csize],
                              "pages": pages[i:i + csize],
                              "src_tiers": [n.residency
                                            for n in tail[i:i + csize]]}
                             for i in range(0, len(tail), csize)],
                 "t0": t0, "deadline": t0 + self.promo_timeout_s,
                 "installed_rows": 0, "src_tiers": []}
        while promo["waiting"] and len(promo["chunks"]) < self.promo_slots:
            if not self._submit_promo_chunk(promo):
                for ch in promo["chunks"] + promo["waiting"]:
                    self._free_pages.extend(ch["pages"])
                self.prefix_cache.unpin(pinned)
                self._promo_fail_c.inc()
                self.prefix_cache.promotion_failures += 1
                self._promo_denied.add(req.rid)
                # in-flight chunks are orphaned to the worker; their
                # staged arrays are dropped on arrival (no install record)
                return False
        self._promo = promo
        for n in tail:
            n.promo = promo
        return True

    def _cancel_promotion(self, deny: bool):
        """Abandon the promotion stream: every NOT-yet-installed chunk's
        reserved pages go back to the pool, the path is unpinned. Chunks
        already installed stay — they are cache-owned device pages now
        (a partial promotion just deepens the device prefix). ``deny``
        marks it a FAILURE (timeout/error/lost the page race) — the
        request won't retry and full-prefills instead; deny=False is the
        benign head-changed path."""
        promo, self._promo = self._promo, None
        for n in promo["nodes"]:
            n.promo = None
        self.prefix_cache.unpin(promo["pinned"])
        self._free_pages.extend(promo["pages"])
        if deny:
            self._promo_fail_c.inc()
            self.prefix_cache.promotion_failures += 1
            self._promo_denied.add(promo["req"].rid)

    def _install_chunk(self, promo, chunk, staged):
        """Land one completed chunk's staged arrays in the pool and hand
        its pages to the cache. Main thread only: compiled decode steps
        donate and replace the pool arrays every step — a background
        thread could write into a donated buffer."""
        for node, page, blob, nb in zip(chunk["nodes"], chunk["pages"],
                                        staged, chunk["nbytes"]):
            if isinstance(blob, dict) and blob.get("ts") is not None:
                # tier_quant blob: decode int8+scale back to fp before
                # the pool scatter. Timed — this is the promotion-side
                # cost tier_quant pays, and the ledger prices it.
                tq0 = _time.perf_counter()
                blob = {"t": self._dequant_rows(blob["t"], blob["ts"]),
                        **({"d": self._dequant_rows(blob["d"], blob["ds"])}
                           if "d" in blob else {})}
                self._dequant_h.observe(_time.perf_counter() - tq0)
            for li, (k_s, v_s) in enumerate(blob["t"]):
                kc, vc = self._state["layers"][li]
                kc._data = kc._data.at[page].set(k_s)
                vc._data = vc._data.at[page].set(v_s)
            if self.draft_model is not None and "d" in blob:
                for li, (k_s, v_s) in enumerate(blob["d"]):
                    kc, vc = self._dstate["layers"][li]
                    kc._data = kc._data.at[page].set(k_s)
                    vc._data = vc._data.at[page].set(v_s)
            self.prefix_cache.promote_node(node, page, nb)
            node.promo = None
        promo["installed_rows"] += len(chunk["nodes"]) * self.block_size
        promo["src_tiers"].extend(chunk["src_tiers"])
        remaining = set(id(n) for n in chunk["nodes"])
        promo["nodes"] = [n for n in promo["nodes"]
                          if id(n) not in remaining]
        drop = set(chunk["pages"])
        promo["pages"] = [p for p in promo["pages"] if p not in drop]

    def _poll_promotion(self) -> str:
        """Advance the promotion stream: 'pending' while transfers run
        (decode steps keep going — that's the overlap), 'ok' once every
        chunk has installed at a step boundary, 'failed' on error/
        timeout (remaining reserved pages reclaimed; chunks already
        installed stay, deepening the device prefix). Each completed
        chunk refreshes the deadline — the timeout bounds PROGRESS, not
        total stream time, so a long cold resume isn't penalized for its
        length."""
        promo = self._promo
        while promo["chunks"]:
            head = promo["chunks"][0]
            fut = head["future"]
            if not fut.done():
                if _time.perf_counter() < promo["deadline"]:
                    return "pending"
                self._cancel_promotion(deny=True)
                return "failed"
            try:
                staged = fut.result()
            except Exception:
                self._cancel_promotion(deny=True)
                return "failed"
            self._install_chunk(promo, head, staged)
            promo["chunks"].pop(0)
            promo["deadline"] = _time.perf_counter() + self.promo_timeout_s
            while (promo["waiting"]
                   and len(promo["chunks"]) < self.promo_slots):
                if not self._submit_promo_chunk(promo):
                    self._cancel_promotion(deny=True)
                    return "failed"
        if promo["waiting"]:           # pragma: no cover — defensive
            self._cancel_promotion(deny=True)
            return "failed"
        self.prefix_cache.unpin(promo["pinned"])
        self._promote_h.observe(_time.perf_counter() - promo["t0"])
        self._promo_c.inc(promo["installed_rows"] // self.block_size)
        self._promo_installed_rows = promo["installed_rows"]
        self._promo_src_tiers = list(promo["src_tiers"])
        self._promo = None
        return "ok"

    def close(self):
        """Retire the async promotion worker (idempotent; the worker is
        a daemon thread, so skipping this only delays cleanup)."""
        if self._promoter is not None:
            self._promoter.close()

    def _release_row(self, row: np.ndarray, keep=()):
        """Reset a block-table row to scratch, returning its pages to the
        free list — except ``keep`` (pages the prefix cache owns: the
        cache's refcounts, not this row, decide their lifetime)."""
        for b in range(self.blocks_per_seq):
            if row[b] != self._scratch:
                if int(row[b]) not in keep:
                    self._free_pages.append(int(row[b]))
                row[b] = self._scratch

    def _release_slot(self, slot: int):
        keep = ()
        if self.prefix_cache is not None:
            nodes = self._slot_nodes.pop(slot, None)
            if nodes:
                self.prefix_cache.unpin(nodes)
                keep = {n.page for n in nodes}
        self._release_row(self._bt[slot], keep)
        self._dec[slot] = 0
        if self.draft_model is not None:
            self._ddec[slot] = 0
        if self.cache_quant:
            for layer in self._scales_np:
                for k in layer:
                    layer[k][slot] = 1.0
            self._scales_dirty = True
        self._free_slots.append(slot)
        self._admit_order.remove(slot)
        self.audit_pages()

    def audit_pages(self) -> int:
        """Set-reconcile the page pool after every release: free list ∪
        block-table rows ∪ prefix-cache pages must cover range(n_pages)
        exactly once (block-table ∩ cache overlap is the POINT — shared
        prefixes — but free ∩ anything is a double-free). Publishes
        ``serving.pages_leaked`` and raises on any anomaly, so a leak
        fails the releasing operation instead of surfacing as OOM much
        later. Returns the leak count (always 0 on the non-raising
        path)."""
        free_set = set(self._free_pages)
        used = set()
        for slot in range(self.max_batch):
            for b in self._bt[slot]:
                if b != self._scratch:
                    used.add(int(b))
        adm = self._admitting
        if adm is not None:
            for b in adm["row"]:
                if b != self._scratch:
                    used.add(int(b))
        if self._promo is not None:
            # pages reserved for an in-flight promotion are spoken for
            used.update(int(p) for p in self._promo["pages"])
        cache_pages = set()
        if self.prefix_cache is not None:
            cp = self.prefix_cache.pages()
            cache_pages = set(cp)
            if len(cache_pages) != len(cp):
                raise RuntimeError("page accounting bug: prefix cache "
                                   "holds a page in two nodes")
        leaked = set(range(self.n_pages)) - free_set - used - cache_pages
        self._pages_leaked_g.set(len(leaked))
        if len(free_set) != len(self._free_pages):
            raise RuntimeError("page accounting bug: free list holds a "
                               "page twice")
        double = free_set & (used | cache_pages)
        if leaked or double:
            raise RuntimeError(
                f"page accounting bug: leaked={sorted(leaked)} "
                f"free-but-used={sorted(double)}")
        if self.prefix_cache is not None:
            # cross-tier half of the audit: host/disk blob byte
            # accounting must reconcile exactly too
            rep = self.prefix_cache.audit_tiers()
            self._host_bytes_g.set(rep.get("host_bytes", 0))
        return 0

    @property
    def free_page_count(self) -> int:
        return len(self._free_pages)

    # -- durable sessions ---------------------------------------------------
    def _session_gauge(self):
        if not hasattr(self, "_session_pin_g"):
            from ..observability.metrics import get_registry
            self._session_pin_g = get_registry().gauge(
                "session.pinned_blocks",
                "prefix-cache blocks currently held by session pins")
        return self._session_pin_g

    def model_identity(self) -> str:
        from .session_store import model_identity
        return model_identity(self.model)

    def pin_session(self, session_id: str, token_ids) -> int:
        """Session-pin the cached chain covering ``token_ids``' full
        blocks (replacing any previous pin for this id): churn may demote
        the chain to host/disk but can no longer drop it out of the last
        tier, so a resume finds it promotable. Local-only — durability
        across replicas is the manifest's job (``pause_session``).
        Returns the number of pinned blocks."""
        if self.prefix_cache is None:
            return 0
        self.unpin_session(session_id)
        path = self.prefix_cache.match(token_ids)
        if path:
            self.prefix_cache.session_pin(path)
            self._session_pins[session_id] = path
        self._session_gauge().set(
            sum(len(p) for p in self._session_pins.values()))
        from ..observability.fleet import spool_event
        spool_event("session", op="pin", session=session_id,
                    blocks=len(path))
        return len(path)

    def unpin_session(self, session_id: str) -> bool:
        nodes = self._session_pins.pop(session_id, None)
        if not nodes:
            return False
        self.prefix_cache.session_unpin(nodes)
        self._session_gauge().set(
            sum(len(p) for p in self._session_pins.values()))
        return True

    def release_sessions(self):
        """Drop every local session pin (manifests are untouched — the
        sessions stay resumable elsewhere). The close/remove path."""
        for sid in list(self._session_pins):
            self.unpin_session(sid)

    def pause_session(self, session_id: str, token_ids) -> bool:
        """Pause a conversation: pin its chain locally AND publish the
        crash-safe manifest (id -> chain hashes + tokens + model identity)
        to the shared store, so ANY replica can resume it later. True iff
        the manifest published atomically; on a torn publish (chaos, IO)
        the chain stays pinned locally — a same-replica resume still
        rides the cache, a cross-replica one falls back to re-prefill."""
        self.pin_session(session_id, token_ids)
        if self.session_store is None:
            return False
        from .session_store import SessionManifest
        toks = np.asarray(token_ids, np.int64).reshape(-1)
        m = SessionManifest(session_id=session_id,
                            token_ids=[int(t) for t in toks],
                            block_size=self.block_size,
                            model=self.model_identity())
        return self.session_store.publish(m)

    def resume_session(self, session_id: str):
        """Resolve a paused session to the token ids to resubmit
        (``prompt ⧺ generated`` of the paused turn — submitting them plus
        the new turn re-matches the pinned chain and streams the tiered
        promotion). ``None`` when the manifest is missing/torn/corrupt or
        the model identity changed (typed finding in the store; the
        caller full-prefills from its own context — token-exact either
        way)."""
        if self.session_store is None:
            return None
        m = self.session_store.load(session_id,
                                    expect_model=self.model_identity())
        if m is None:
            return None
        # a block_size mismatch only invalidates the manifest's chain
        # hashes (a routing hint); the tokens stay good — the radix tree
        # matches raw token blocks, so resume correctness is unaffected
        return np.asarray(m.token_ids, np.int64)

    # -- request lifecycle --------------------------------------------------
    def _validate(self, prompt: np.ndarray, max_new_tokens: int):
        super()._validate(prompt, max_new_tokens)
        worst = len(prompt) + max_new_tokens
        if self.prefill_chunk:
            # chunk padding can demand more rows than the timeline (a
            # preemption-resume prompt pads up to one chunk beyond);
            # reject now rather than livelock admission later
            worst = max(worst, min(
                -(-worst // self.prefill_chunk) * self.prefill_chunk,
                self.blocks_per_seq * self.block_size))
        elif self._prompt_ladder is not None:
            # same hazard as chunk padding: the ladder can round a
            # resume-length prompt past the timeline
            worst = max(worst, min(self._prompt_ladder.bucket(worst),
                                   self.blocks_per_seq * self.block_size))
        pages = self._pages_for(worst)
        if pages > self.n_pages:
            raise ValueError(f"request needs {pages} pages but the pool "
                             f"holds {self.n_pages}")

    def _admit(self) -> List[int]:
        """FIFO admission into free slots, gated by page availability
        (reserve: worst case up front; ondemand: prompt + first step).
        Head-of-line blocking is deliberate — it preserves arrival order
        the way the reference's serving queue does."""
        import paddle_tpu as paddle
        finished = []
        if self._promo is not None and (
                not self._pending
                or self._pending[0] is not self._promo["req"]):
            # the promotion's request left the head (expired, requeued):
            # benign cancel, pages back
            self._cancel_promotion(deny=False)
        while self._pending and self._free_slots:
            req = self._pending[0]
            # a preempted request resumes from prompt ⧺ generated; chunked
            # prefill pads to the chunk width (capacity-clamped)
            ids_full = np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int64)]) \
                if req.tokens else req.prompt
            matched = []
            promoted_rows = 0
            src_tiers: List[str] = []
            if self.prefix_cache is not None:
                # cap at (L-1)//bs blocks: at least one suffix token must
                # prefill — the first generated token needs logits, and a
                # fully-cached prompt has none to offer
                cap_blocks = (len(ids_full) - 1) // self.block_size
                matched = self.prefix_cache.match(ids_full,
                                                  max_blocks=cap_blocks)
                dev, tail = self.prefix_cache.split_device(matched)
                if self._promo is not None:
                    st = self._poll_promotion()
                    if st == "pending":
                        if not self._slot_req:
                            # nothing to overlap with: don't hot-spin the
                            # step loop while the transfer lands
                            _time.sleep(500e-6)
                        break
                    # ok: the tail is device-resident now; failed: the
                    # tail stays off-device and is skipped below — either
                    # way re-split the fresh tree state
                    matched = self.prefix_cache.match(ids_full,
                                                      max_blocks=cap_blocks)
                    dev, tail = self.prefix_cache.split_device(matched)
                    matched = dev
                    if st == "ok":
                        promoted_rows = self._promo_installed_rows
                        src_tiers = self._promo_src_tiers
                elif (tail and self._promoter is not None
                        and req.rid not in self._promo_denied):
                    if self._start_promotion(req, dev, tail):
                        break     # decode steps continue while it flies
                    matched = dev
                else:
                    # off-device tail unusable (no promoter, or this
                    # request already burned its promotion): prefill it
                    # fresh — insert() upgrades the stale nodes in place
                    matched = dev
                if matched:
                    # pin BEFORE the availability gate: the gate may
                    # admit on the promise of evicting OTHER chains, and
                    # eviction must not be able to take these pages
                    self.prefix_cache.pin(matched)
            m_rows = len(matched) * self.block_size
            ids_np, L, padded_len, upto = self._admission_plan(req, m_rows)
            need = self._pages_for(upto) - len(matched)
            if need > len(self._free_pages) + (
                    self.prefix_cache.evictable_pages()
                    if self.prefix_cache is not None else 0):
                if matched:
                    self.prefix_cache.unpin(matched)
                break
            with self._intake:
                self._pending.pop(0)
            self._promo_denied.discard(req.rid)
            slot = self._free_slots.pop(0)
            if matched:
                self._bt[slot, :len(matched)] = [n.page for n in matched]
            if not self._alloc_pages(slot, upto):
                raise RuntimeError("page accounting bug: admission gate "
                                   "passed but allocation failed")
            self._trace_admit_begin(req)
            self._trace_prefill_begin(req)
            bt_row = paddle.to_tensor(self._bt[slot:slot + 1])
            S = L - m_rows
            with paddle.no_grad():
                if self.prefill_chunk:
                    logits = self._prefill_chunked(ids_np[m_rows:], bt_row,
                                                   slot, dec0=m_rows)
                elif self.cache_quant:
                    ids = paddle.to_tensor(ids_np[None, :])
                    logits, self._state["layers"], seq_scales = \
                        self.model.paged_prefill_into(
                            ids, self._state["layers"], bt_row,
                            self.block_size, dynamic_cache_scales=True)
                    self._store_slot_scales(slot, seq_scales)
                elif m_rows or self._prompt_ladder is not None:
                    # suffix prefill: append S real tokens after the
                    # m_rows cached rows, padded up to the resolved rung
                    # (pad rows sit past the timeline — stale until
                    # decode overwrites them, never read before that)
                    pad_s = padded_len - m_rows
                    if pad_s != S:
                        self._count_pad_waste(pad_s, pad_s - S)
                    sfx = np.zeros((pad_s,), np.int64)
                    sfx[:S] = ids_np[m_rows:]
                    logits, self._state["layers"] = \
                        self.model.paged_prefill_into(
                            paddle.to_tensor(sfx[None, :]),
                            self._state["layers"], bt_row,
                            self.block_size,
                            dec_base=paddle.to_tensor(
                                np.array([m_rows], np.int32)),
                            logits_at=paddle.to_tensor(
                                np.array([S - 1], np.int32)))
                else:
                    ids = paddle.to_tensor(ids_np[None, :])
                    logits, self._state["layers"] = \
                        self.model.paged_prefill_into(
                            ids, self._state["layers"], bt_row,
                            self.block_size)
                if self.draft_model is not None:
                    # mirror the suffix into the DRAFT pool (same block-
                    # table row, its own physical pages); cached pages
                    # already hold this prefix's draft rows — every page
                    # enters the tree through an admission that wrote
                    # both pools
                    dfx = np.zeros((max(S, 1),), np.int64)
                    dfx[:S] = ids_np[m_rows:]
                    _dl, self._dstate["layers"] = \
                        self.draft_model.paged_prefill_into(
                            paddle.to_tensor(dfx[None, :]),
                            self._dstate["layers"], bt_row,
                            self.block_size,
                            dec_base=paddle.to_tensor(
                                np.array([m_rows], np.int32)),
                            logits_at=paddle.to_tensor(
                                np.array([0], np.int32)))
                    self._ddec[slot] = L
            if self.prefix_cache is not None:
                self._prefix_hit_c.inc(m_rows)
                self._prefix_miss_c.inc(S)
                self.prefix_cache.hit_tokens += m_rows
                self.prefix_cache.miss_tokens += S
                self._tier_hit_c.labels(tier="device").inc(
                    m_rows - promoted_rows)
                for t in src_tiers:
                    self._tier_hit_c.labels(tier=t).inc(self.block_size)
                self.prefix_cache.host_hit_tokens += promoted_rows
                new_nodes = self.prefix_cache.insert(
                    ids_np, self._bt[slot], len(matched),
                    L // self.block_size)
                self._slot_nodes[slot] = list(matched) + new_nodes
            end_tags = dict(prompt_tokens=len(ids_np), pages=need,
                            prefix_hit=m_rows, padded_to=padded_len)
            if promoted_rows:
                # the ledger splits evicted_prefix_recompute pricing on
                # this: a promoted resume repaid its eviction from the
                # host tier, not by recomputing
                end_tags["host_promoted"] = promoted_rows
            self._trace_prefill_end(req, **end_tags)
            tok = int(self._pick(np.asarray(logits._data))[0])
            req.slot = slot
            req.tokens.append(tok)
            self._tele.on_admit()
            self._tele.on_token(req)
            self._slot_req[slot] = req
            self._admit_order.append(slot)
            self._dec[slot] = len(ids_np)
            self._last_tok[slot] = tok
            self._trace_admit_end(req, slot)
            if self._maybe_finish(req, tok):
                finished.append(req.rid)
        return finished

    def _count_pad_waste(self, rung: int, waste: int):
        from ..observability.metrics import get_registry
        get_registry().counter(
            "serving.bucket_pad_waste",
            "pad tokens admission added to reach the prompt bucket",
            labelnames=("rung",)).labels(rung=str(rung)).inc(waste)

    def _prefill_chunked(self, ids_np, bt_row, slot, dec0: int = 0):
        """Feed the prompt through fixed-width append chunks (ONE compiled
        executable for every prompt length). The tail chunk is zero-padded;
        pad rows land past the true timeline and are overwritten by decode
        before any bounded read reaches them. Returns the last REAL
        position's logits [1, V].

        Dynamic cachekv-int8 composition (VERDICT r3 #5): with
        cache_quant set, chunk 1 computes the sequence's per-head scales
        from its VALID rows (the zero-pad tail is masked out of the amax
        statistics, matching what an unpadded single-call prefill would
        compute) and returns them; every later chunk — and decode —
        quantizes with those same scales, so the timeline is scale-
        consistent end to end. For prompts within the chunk width this is
        exactly the unchunked dynamic path, token-for-token; longer
        prompts derive their scales from the first chunk's rows, the same
        first-window semantics the reference's serving stack uses when
        scales must exist before the whole prompt has been seen.

        ``dec0``: cached-prefix offset — ``ids_np`` is the SUFFIX and the
        chunks append after ``dec0`` existing rows (prefix-cache hits;
        always 0 on the quantized path, which is gated off prefix reuse).
        """
        import paddle_tpu as paddle
        C = self.prefill_chunk
        L = len(ids_np)
        cap = self.blocks_per_seq * self.block_size
        padded_len = min(-(-L // C) * C, cap - dec0)
        padded = np.zeros((padded_len,), np.int64)
        padded[:L] = ids_np
        dec = 0
        logits = None
        scales = None
        last_rest = None          # (dec, nvalid) of the last rest chunk
        first_nvalid = 0          # valid rows in the scale-setting chunk
        while dec < padded_len:
            w = min(C, padded_len - dec)     # tail shortens at capacity
            has_last = 0 <= (L - 1) - dec < w
            at = (L - 1) - dec if has_last else 0
            ids_t = paddle.to_tensor(padded[None, dec:dec + w])
            dec_t = paddle.to_tensor(np.array([dec0 + dec], np.int32))
            at_t = paddle.to_tensor(np.array([at], np.int32))
            if not self.cache_quant:
                lg, self._state["layers"] = self._chunk_fn(
                    ids_t, self._state["layers"], bt_row, dec_t, at_t)
            elif scales is None:
                first_nvalid = min(L - dec, w)
                nvalid = paddle.to_tensor(
                    np.array([first_nvalid], np.int32))
                lg, self._state["layers"], scales = \
                    self._chunk_dyn_first_fn(
                        ids_t, self._state["layers"], bt_row, dec_t,
                        at_t, nvalid)
            else:
                lg, self._state["layers"] = self._chunk_dyn_rest_fn(
                    ids_t, self._state["layers"], bt_row, dec_t, at_t,
                    scales)
                if L - dec > 0:
                    last_rest = (dec, min(L - dec, w))
            if has_last:
                # the final chunk always contains position L-1 (its start
                # k*C < L by the ceil-padding construction)
                logits = lg
            dec += w
        if scales is not None:
            if last_rest is not None:
                # sampled saturation telemetry: one baseline read of the
                # scale-setting chunk, one read of the final rest chunk
                base = self._topbin_counts(bt_row, 0, first_nvalid)
                self._record_chunk_saturation(
                    bt_row, last_rest[0], last_rest[1],
                    baseline=None if base is None
                    else base[0] / max(base[1], 1))
            self._store_slot_scales(slot, scales)
        return logits

    def _topbin_counts(self, bt_row, dec, nvalid):
        """(top_bin_entries, total_entries) over the int8 K/V rows at
        positions [dec, dec+nvalid) of this slot, or None if the pool is
        not quantized. |q| >= 127 is a PROXY: true saturation and
        legitimately-in-range values within ~0.4% of amax both land in
        the top bin, which is why the warning below is baseline-relative
        rather than absolute."""
        if nvalid <= 0:
            return None
        bt = np.asarray(getattr(bt_row, "_data", bt_row))[0]
        pos = np.arange(dec, dec + nvalid)
        phys = bt[pos // self.block_size]
        off = pos % self.block_size
        clipped = total = 0
        for kc, vc in self._state["layers"]:
            for pool in (kc, vc):
                arr = np.asarray(getattr(pool, "_data", pool)[phys, :, off])
                if arr.dtype != np.int8:
                    return None
                clipped += int((np.abs(arr.astype(np.int32)) >= 127).sum())
                total += arr.size
        return clipped, total

    def _record_chunk_saturation(self, bt_row, dec, nvalid,
                                 baseline=None):
        """First-window telemetry (ADVICE r4, serving.py:605): later
        chunks quantize with chunk-1 scales, so K/V values above the
        stored amax saturate at +/-127 with no other trace. SAMPLED —
        the chunk loop calls this once per prompt (its last rest chunk,
        plus one baseline read of the scale-setting first chunk), so the
        cost is two small device->host reads per prompt, not per chunk.
        Warns ONCE when the rest-chunk top-bin rate exceeds
        max(1%, 3 x the first chunk's own top-bin rate) — the first
        chunk's rate is the legitimate near-amax baseline, so growth
        beyond it indicates real saturation, not a peaked distribution."""
        counts = self._topbin_counts(bt_row, dec, nvalid)
        if counts is None:
            return
        clipped, total = counts
        self._tele.on_cachekv(clipped, total)
        rate = clipped / max(total, 1)
        threshold = max(0.01, 3.0 * (baseline or 0.0))
        if rate > threshold and not self._tele.warned_cachekv_clip:
            self._tele.warned_cachekv_clip = True
            import warnings
            warnings.warn(
                f"cachekv-int8 chunked prefill: {rate:.1%} of a later "
                f"chunk's K/V entries sit in the top quantization bin "
                f"(baseline {0.0 if baseline is None else baseline:.1%}) "
                f"— values likely exceed the first-chunk scales "
                f"(documented first-window semantics); long-prompt "
                f"accuracy may degrade. stats()['cachekv_clip_rate'] "
                f"tracks the sampled rate.",
                RuntimeWarning, stacklevel=2)

    def _store_slot_scales(self, slot, seq_scales):
        """Copy a 1-sequence prefill's per-layer scale dicts into the
        slot's host-owned scale rows (decode reads them from the state)."""
        for li, sc in enumerate(seq_scales):
            for k in ("kq", "vq", "kdq", "vdq"):
                self._scales_np[li][k][slot] = np.asarray(sc[k]._data)[0]
        self._scales_dirty = True

    def _sync_tables(self):
        import paddle_tpu as paddle
        self._state["block_tables"] = paddle.to_tensor(self._bt)
        self._state["dec_lens"] = paddle.to_tensor(self._dec)
        # a compiled step returns the pass-through python ints as 0-d
        # arrays; restore them so the NEXT call's signature (and its
        # executable) stays identical
        self._state["block_size"] = self.block_size
        self._state["capacity"] = self.blocks_per_seq * self.block_size
        if self.cache_quant and self._scales_dirty:
            # scales change only at admit/release — skip the L x 4
            # re-uploads on the steady-state decode path
            self._state["cache_scales"] = [
                {k: paddle.to_tensor(layer[k]) for k in layer}
                for layer in self._scales_np]
            self._scales_dirty = False

    def _preempt_latest(self, protect: int) -> bool:
        """Evict the most-recently admitted active request (≠ protect) back
        to the FRONT of the queue; its pages return to the pool. Returns
        False when no victim exists."""
        for slot in reversed(self._admit_order):
            if slot == protect:
                continue
            req = self._slot_req.pop(slot)
            req.slot = None
            self._release_slot(slot)
            with self._intake:
                self._pending.insert(0, req)
            self._trace_close(req, preempted=1)
            self._tele.on_preempt()
            return True
        return False

    def _grow_for_step(self):
        """ondemand: every active slot is about to write kv row dec[slot];
        back it with a page, preempting (slots, then any in-flight fused
        admission) if the pool is dry."""
        for slot in list(self._admit_order):
            if slot not in self._slot_req:
                continue
            while not self._alloc_pages(slot, int(self._dec[slot]) + 1):
                if self._promo is not None:
                    # an in-flight promotion loses the race to live
                    # decode: reclaim its reserved pages before touching
                    # any live request (its admission full-prefills)
                    self._cancel_promotion(deny=True)
                    continue
                if self._preempt_latest(protect=slot):
                    continue
                if self._admitting is not None:
                    # the admission's detached row holds pages too —
                    # evict it rather than failing a live decode
                    self._abort_admission()
                    continue
                raise RuntimeError(
                    f"page pool exhausted: slot {slot} needs a page at "
                    f"row {int(self._dec[slot])}, no free pages and no "
                    f"other request to preempt (n_pages={self.n_pages})")

    # -- fused admission (vLLM unified scheduling) --------------------------
    def _has_work(self) -> bool:
        return bool(self._pending or self._slot_req or self._admitting)

    def _admission_plan(self, req: Request, m_rows: int = 0):
        """The ONE home of the resume-ids / chunk-padding / page-budget
        arithmetic (used by synchronous admission and the fused path).
        ``m_rows`` is the cached-prefix row count: only the SUFFIX is
        prefilled, so chunk/ladder padding applies to the suffix and is
        clamped to the capacity left after the cached rows (pad rows past
        capacity would clip-index the block table and corrupt the last
        real page)."""
        ids_np = np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int64)]) \
            if req.tokens else req.prompt
        L = len(ids_np)
        S = L - m_rows
        cap = self.blocks_per_seq * self.block_size
        if self.prefill_chunk:
            pad_s = min(-(-S // self.prefill_chunk) * self.prefill_chunk,
                        cap - m_rows)
        elif self._prompt_ladder is not None:
            pad_s = min(self._prompt_ladder.bucket(S), cap - m_rows)
        else:
            pad_s = S
        padded_len = m_rows + pad_s
        if self.policy == "reserve":
            upto = max(padded_len, L + req.max_new_tokens - len(req.tokens))
        else:
            upto = max(padded_len, L + 1)
        return ids_np, L, padded_len, upto

    def _start_admission(self) -> bool:
        """Reserve a slot + pages for the next pending request; its
        prompt then streams through the fused step one chunk per step
        while the other slots keep decoding."""
        if self._admitting or not self._pending or not self._free_slots:
            return False
        req = self._pending[0]
        ids_np, L, padded_len, upto = self._admission_plan(req)
        if self._pages_for(upto) > len(self._free_pages):
            return False
        with self._intake:
            self._pending.pop(0)
        slot = self._free_slots.pop(0)
        row = np.full((self.blocks_per_seq,), self._scratch, np.int32)
        if not self._alloc_pages_row(row, upto):
            raise RuntimeError("page accounting bug: admission gate "
                               "passed but allocation failed")
        padded = np.zeros((padded_len,), np.int64)
        padded[:L] = ids_np
        # the slot's MAIN row stays scratch until admission completes, so
        # its garbage decode writes land in the scratch page instead of
        # the rows the chunks are filling
        self._admitting = {"req": req, "slot": slot, "row": row,
                           "ids": padded, "L": L, "offset": 0}
        self._trace_admit_begin(req)
        self._trace_prefill_begin(req)
        return True

    def _abort_admission(self):
        """Preempt the in-flight admission: pages back to the pool, the
        request to the FRONT of the queue (offset resets; recompute on
        resume is exact, same as slot preemption)."""
        adm = self._admitting
        self._release_row(adm["row"])
        self._free_slots.append(adm["slot"])
        self._pending.insert(0, adm["req"])
        self._admitting = None
        self._trace_close(adm["req"], preempted=1)
        self._tele.on_preempt()
        self.audit_pages()

    def _fused_chunk_inputs(self):
        import paddle_tpu as paddle
        adm = self._admitting
        if adm is None:
            return self._idle_chunk
        C = self.prefill_chunk
        o = adm["offset"]
        ids = adm["ids"][o:o + C]   # always full width: cap % C == 0
        at = adm["L"] - 1 - o
        at = at if 0 <= at < C else 0
        return (paddle.to_tensor(ids),
                paddle.to_tensor(adm["row"][None, :]),
                paddle.to_tensor(np.array([o], np.int32)),
                paddle.to_tensor(np.array([at], np.int32)))

    def _finish_admission(self, chunk_logits, finished: List[int]):
        """Advance the in-flight admission by one chunk; on the final
        chunk, install the block-table row and promote the request to a
        decoding slot."""
        adm = self._admitting
        if adm is None:
            return
        C = self.prefill_chunk
        o, L = adm["offset"], adm["L"]
        had_last = o <= L - 1 < o + C
        adm["offset"] = o + C
        if not had_last:
            return
        req, slot = adm["req"], adm["slot"]
        self._trace_prefill_end(req, prompt_tokens=L, fused=1)
        tok = int(self._pick(np.asarray(chunk_logits._data))[0])
        self._bt[slot] = adm["row"]
        self._dec[slot] = L
        self._last_tok[slot] = tok
        req.slot = slot
        req.tokens.append(tok)
        self._tele.on_admit()
        self._tele.on_token(req)
        self._slot_req[slot] = req
        self._admit_order.append(slot)
        self._admitting = None
        self._trace_admit_end(req, slot)
        if self._maybe_finish(req, tok):
            finished.append(req.rid)

    def _step_fused(self) -> List[int]:
        """One fused executable call: every decode slot advances AND the
        in-flight admission streams its next chunk — decode throughput
        never pauses for a prefill. With NO admission in flight the plain
        decode executable runs instead: an idle chunk would still compute
        C token positions through the model for nothing."""
        import paddle_tpu as paddle
        finished: List[int] = []
        self._start_admission()
        if self._admitting is None:
            self._decode_tail(finished)
            return finished
        self._step_prologue()
        n_active = len(self._slot_req)
        t0 = _time.perf_counter()
        tok_t = paddle.to_tensor(self._last_tok)
        ids_t, row_t, dec_t, at_t = self._fused_chunk_inputs()
        with paddle.no_grad():
            dec_logits, chunk_logits, self._state = self._fused_fn(
                tok_t, ids_t, row_t, dec_t, at_t, self._state)
        self._advance_decoders(dec_logits, finished)
        self._finish_admission(chunk_logits, finished)
        self._tele.on_decode_time(_time.perf_counter() - t0,
                                  tokens=n_active)
        return finished

    def _advance_decoders(self, logits, finished: List[int]):
        """Consume a step's decode logits: advance timelines, append the
        picked tokens, evict finished slots."""
        self._dec += np.asarray(self._slot_active_mask(), np.int32)
        next_tok = self._pick(np.asarray(logits._data))
        for slot, req in list(self._slot_req.items()):
            tok = int(next_tok[slot])
            req.tokens.append(tok)
            self._tele.on_token(req)
            self._last_tok[slot] = tok
            if self._maybe_finish(req, tok):
                finished.append(req.rid)

    def _step_prologue(self):
        """Shared pre-decode bookkeeping: on-demand page growth, step
        counters, and the host->device table sync. The HOST owns the
        block table and the timeline: re-uploading both every step (tiny
        int32 arrays) keeps parked slots from drifting — the device step
        increments dec_lens for all B slots, the host only for active
        ones."""
        if self.policy == "ondemand":
            self._grow_for_step()
        self._tele.on_step()
        self._tele.on_occupancy(len(self._slot_req))
        self._tele.set_gauges(len(self._pending), len(self._slot_req))
        self._sync_tables()

    def _decode_tail(self, finished: List[int]):
        """The decode-only step body (shared by the plain engine and the
        fused engine's idle steps)."""
        import paddle_tpu as paddle
        if not self._slot_req:
            return
        if self.draft_model is not None \
                and self._speculative_tail(finished):
            return
        if self.decode_block and not self._pending \
                and self._admitting is None \
                and self._block_backed(self.decode_block):
            self._decode_block_tail(finished)
            return
        self._step_prologue()
        n_active = len(self._slot_req)
        t0 = _time.perf_counter()
        tok_t = paddle.to_tensor(self._last_tok)
        with paddle.no_grad():
            logits, self._state = self._step_fn(tok_t, self._state)
        self._advance_decoders(logits, finished)
        self._tele.on_decode_time(_time.perf_counter() - t0,
                                  tokens=n_active)

    def _block_backed(self, K: int) -> bool:
        """A K-step block is safe when, for every active slot, the rows
        it will KEEP are page-backed and dec+K stays inside the slot
        window. Rows a slot writes past its remaining budget (it gets
        evicted at max_new anyway) or past its backed pages land in the
        SCRATCH page (unbacked block-table entries stay scratch), so
        only the keep-rows need real pages. Growth here never preempts —
        a dry pool falls back to the per-step path, whose preemption
        logic stays the single source of that policy. Feasibility is
        probed for ALL slots before ANY page moves: a declined block
        must not leave earlier slots hoarding pages they will not use
        for K more steps (that would push the per-step path into
        preemptions the probe itself caused)."""
        cap = self.blocks_per_seq * self.block_size
        plan = []                      # (slot, upto) to allocate on pass
        need = 0
        for slot in list(self._admit_order):
            req = self._slot_req.get(slot)
            if req is None:
                continue
            if int(self._dec[slot]) + K > cap:
                return False
            keep = min(K, req.max_new_tokens - len(req.tokens))
            if keep <= 0:
                continue
            upto = int(self._dec[slot]) + keep
            have = int(np.sum(self._bt[slot] != self._scratch))
            need += max(0, self._pages_for(upto) - have)
            plan.append((slot, upto))
        if self.policy != "ondemand":
            return True                # reserve backed everything upfront
        if need > self._available_pages():
            return False
        for slot, upto in plan:
            if not self._alloc_pages(slot, upto):   # pragma: no cover
                raise RuntimeError("page accounting bug: block probe "
                                   "passed but allocation failed")
        return True

    def _decode_block_tail(self, finished: List[int]):
        """Run one compiled K-step decode block and consume its K*B
        tokens on the host: per sub-step, append to each still-live
        request, finishing/evicting exactly as the per-step path would.
        A slot that finishes mid-block decoded garbage for the remaining
        sub-steps — those tokens are discarded here, and their K/V rows
        went to its own (about-to-be-freed) pages or scratch."""
        import paddle_tpu as paddle
        K = self.decode_block
        self._tele.on_step(K)
        self._tele.on_decode_block()
        self._tele.set_gauges(len(self._pending), len(self._slot_req))
        self._sync_tables()
        n_active = len(self._slot_req)
        t0 = _time.perf_counter()
        tok_t = paddle.to_tensor(self._last_tok)
        with paddle.no_grad():
            toks, self._state = self._block_fn(tok_t, self._state)
        toks_np = np.asarray(toks._data)                  # [K, B]
        self._tele.on_decode_time(_time.perf_counter() - t0, K,
                                  tokens=K * n_active)
        # survivors consumed all K rows; evicted slots' counters are
        # reset at their next admission
        self._dec += K * np.asarray(self._slot_active_mask(), np.int32)
        for k in range(K):
            # occupancy at each sub-step's ENTRY (post prior evictions),
            # matching the per-step path's _step_prologue accounting
            self._tele.on_occupancy(len(self._slot_req))
            for slot, req in list(self._slot_req.items()):
                tok = int(toks_np[k, slot])
                req.tokens.append(tok)
                self._tele.on_token(req)
                self._last_tok[slot] = tok
                if self._maybe_finish(req, tok):
                    finished.append(req.rid)

    # -- in-batcher speculative decoding ------------------------------------
    def _sync_draft_tables(self):
        import paddle_tpu as paddle
        self._dstate["block_tables"] = paddle.to_tensor(self._bt)
        self._dstate["dec_lens"] = paddle.to_tensor(self._ddec)
        self._dstate["block_size"] = self.block_size
        self._dstate["capacity"] = self.blocks_per_seq * self.block_size

    @staticmethod
    def _argmax_b(logits) -> np.ndarray:
        return np.asarray(logits._data).argmax(-1)

    def _speculative_tail(self, finished: List[int]) -> bool:
        """One batched draft/verify round for every active slot; returns
        False (nothing ran) when this round must fall back to the plain
        per-step path, which keeps sole ownership of preemption policy.

        Invariants (the _speculative_loop contract, per slot): the TARGET
        pool holds rows for prompt + tokens[:-1] (``_dec``; tokens[-1] is
        pending), the DRAFT pool holds correct rows for the first
        ``_ddec`` positions. The round appends the draft's catch-up
        (``seq[_ddec:]``, ending at the pending token — its last logits
        are proposal 1), runs k-1 draft steps, then the target scores
        pending + all k proposals in ONE verify pass; each slot accepts
        its longest matching prefix plus the target's own correction, so
        output is the target's greedy sequence token for token."""
        import paddle_tpu as paddle
        reqs = list(self._slot_req.items())
        k = min(self.draft_k,
                min(r.max_new_tokens - len(r.tokens)
                    for _, r in reqs) - 1)
        if k < 1:
            # some slot has budget for exactly one token: a k-wide round
            # would overshoot it, so take one plain step instead
            self.spec_stats["fallback_steps"] += 1
            return False
        cap = self.blocks_per_seq * self.block_size
        cus = {slot: int(self._dec[slot]) - int(self._ddec[slot]) + 1
               for slot, _ in reqs}
        W = self._cu_ladder.bucket(max(cus.values()))
        for slot, _ in reqs:
            # both pools write rows through dec+k; catch-up pad rows
            # reach ddec+W-1 — past-capacity writes would clip-index the
            # block table onto the last REAL page
            if int(self._dec[slot]) + k + 1 > cap \
                    or int(self._ddec[slot]) + W > cap:
                self.spec_stats["fallback_steps"] += 1
                return False
        if self.policy == "ondemand":
            # probe-then-alloc over ALL slots (the _block_backed rule): a
            # declined round must not strand pages it already moved
            plan = []
            need = 0
            for slot, _ in reqs:
                upto = int(self._dec[slot]) + k + 1
                have = int(np.sum(self._bt[slot] != self._scratch))
                need += max(0, self._pages_for(upto) - have)
                plan.append((slot, upto))
            if need > self._available_pages():
                self.spec_stats["fallback_steps"] += 1
                return False
            for slot, upto in plan:
                if not self._alloc_pages(slot, upto):  # pragma: no cover
                    raise RuntimeError("page accounting bug: speculative "
                                       "probe passed but allocation "
                                       "failed")
        self._step_prologue()
        t0 = _time.perf_counter()
        B = self.max_batch
        cu_ids = np.zeros((B, W), np.int64)
        cu_at = np.zeros((B,), np.int32)
        dbase = np.zeros((B,), np.int32)
        for slot, req in reqs:
            seq = np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int64)])
            lo = int(self._ddec[slot])
            cu = seq[lo:]                       # ends at the pending token
            cu_ids[slot, :len(cu)] = cu
            cu_at[slot] = len(cu) - 1
            dbase[slot] = lo
        with paddle.no_grad():
            self._sync_draft_tables()
            dl, self._dstate["layers"] = self._catchup_fn(
                paddle.to_tensor(cu_ids), self._dstate["layers"],
                self._dstate["block_tables"], paddle.to_tensor(dbase),
                paddle.to_tensor(cu_at))
            props = [self._argmax_b(dl)]        # [B] proposal 1
            for slot, _ in reqs:
                self._ddec[slot] = int(self._dec[slot]) + 1
            self._dstate["dec_lens"] = paddle.to_tensor(self._ddec)
            tok = props[0]
            for _ in range(k - 1):
                dlg, self._dstate = self._dstep_fn(
                    paddle.to_tensor(tok.astype(np.int64)), self._dstate)
                tok = self._argmax_b(dlg)
                props.append(tok)
            ids_v = np.zeros((B, k + 1), np.int64)
            for slot, _ in reqs:
                ids_v[slot, 0] = self._last_tok[slot]
                for i in range(k):
                    ids_v[slot, 1 + i] = props[i][slot]
            vlogits, self._state["layers"] = self._verify_fn(
                paddle.to_tensor(ids_v), self._state["layers"],
                self._state["block_tables"],
                paddle.to_tensor(self._dec.copy()))
        g = np.asarray(vlogits._data).argmax(-1)          # [B, k+1]
        total = 0
        for slot, req in reqs:
            pv = [int(props[i][slot]) for i in range(k)]
            j = 0
            while j < k and pv[j] == int(g[slot, j]):
                j += 1
            acc = pv[:j] + [int(g[slot, j])]
            self.spec_stats["proposed"] += k
            self.spec_stats["matched"] += j
            sp = req.spans.get("decode")
            if sp is not None:
                # per-request accept accounting on the OPEN decode span
                # (before _maybe_finish can close it): the goodput
                # ledger prices rejected draft tokens from these
                tg = sp.tags
                tg["spec_proposed"] = int(tg.get("spec_proposed")
                                          or 0) + k
                tg["spec_matched"] = int(tg.get("spec_matched")
                                         or 0) + j
                tg["spec_rounds"] = int(tg.get("spec_rounds") or 0) + 1
            old_dec = int(self._dec[slot])
            self._dec[slot] = old_dec + len(acc)
            self._ddec[slot] = min(old_dec + k, old_dec + len(acc))
            for t in acc:
                if req.finished:
                    break            # EOS mid-round: discard the rest
                req.tokens.append(int(t))
                self._tele.on_token(req)
                self._last_tok[slot] = int(t)
                total += 1
                if self._maybe_finish(req, int(t)):
                    finished.append(req.rid)
        self.spec_stats["rounds"] += 1
        self._tele.on_decode_time(_time.perf_counter() - t0,
                                  tokens=total)
        return True

    # -- the engine ---------------------------------------------------------
    def _step_impl(self) -> List[int]:
        """Admit, grow pages (ondemand), decode one token per active slot,
        evict finished. Returns rids finishing during THIS call."""
        if self.fused_admission:
            return self._step_fused()
        finished = self._admit()
        self._decode_tail(finished)
        return finished

    def _slot_active_mask(self):
        m = np.zeros((self.max_batch,), bool)
        for slot in self._slot_req:
            m[slot] = True
        return m
