"""Continuous batching over the KV-cache decode step.

Reference surface: the serving loop the reference builds around
AnalysisPredictor + block_multihead_attention (dynamic request admission
into a running decode batch). TPU-first design: XLA wants ONE static
shape, so the batcher owns `max_batch` SLOTS — a fixed [L, 2, B, H, S, D]
cache — and the host-side scheduler admits pending requests into free
slots at step boundaries, evicts finished ones, and steps every slot
through one compiled decode executable. Inactive slots decode garbage
into a scratch row that admission's prefill overwrites before any real
read (causality: a slot's attention never reads rows past its own t), so
no per-occupancy recompilation ever happens.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

__all__ = ["ContinuousBatcher", "Request"]


@dataclass
class Request:
    rid: int
    prompt: np.ndarray          # [s] int64
    max_new_tokens: int
    tokens: List[int] = field(default_factory=list)
    slot: Optional[int] = None

    @property
    def done(self) -> bool:
        return self.slot is None and bool(self.tokens)


class ContinuousBatcher:
    """Continuous batcher over a causal LM's dense KV cache.

    model: a GPT2ForCausalLM or LlamaForCausalLM (eval mode — any model
    exposing prefill/decode_step with the [B, 1] t convention). max_batch: slot count (ONE
    compiled decode executable serves every step at this batch). s_max:
    per-slot cache rows (prompt + generation must fit). eos_id: optional
    early-stop token. compile: jit.to_static the decode step (recommended;
    disable for debugging).
    """

    def __init__(self, model, max_batch: int = 8, s_max: int = 256,
                 eos_id: Optional[int] = None, compile: bool = True,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: Optional[float] = None,
                 seed: Optional[int] = None):
        import paddle_tpu as paddle

        self.model = model
        self._do_sample = do_sample
        self._temperature = temperature
        self._top_k = top_k
        self._top_p = top_p
        self._rng = np.random.RandomState(seed)
        self.max_batch = max_batch
        self.s_max = s_max
        self.eos_id = eos_id
        cfg = model.config
        if s_max > cfg.max_position_embeddings:
            raise ValueError(f"s_max={s_max} exceeds "
                             f"max_position_embeddings="
                             f"{cfg.max_position_embeddings}")
        L, d = cfg.num_hidden_layers, cfg.head_dim
        # GQA models cache at kv-head count (unexpanded)
        kvh = getattr(cfg, "num_key_value_heads", None) \
            or cfg.num_attention_heads
        self._caches = paddle.zeros([L, 2, max_batch, kvh, s_max, d],
                                    dtype=cfg.dtype)
        self._t = np.full((max_batch, 1), s_max - 1, np.int32)  # parked
        self._free = list(range(max_batch))
        self._slot_req: Dict[int, Request] = {}
        self._pending: List[Request] = []
        self._finished: Dict[int, Request] = {}
        self._next_rid = 0
        self._last_tok = np.zeros((max_batch, 1), np.int64)
        if compile:
            from .. import jit
            # donate the caches argument (tensor arg index 1): XLA reuses
            # the cache HBM in place instead of double-buffering per step
            self._step_fn = jit.to_static(model.decode_step,
                                          donate_args=(1,))
        else:
            self._step_fn = model.decode_step

    # -- request lifecycle --------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int) -> int:
        prompt = np.asarray(prompt_ids, np.int64).reshape(-1)
        if len(prompt) + max_new_tokens > self.s_max:
            raise ValueError(f"prompt {len(prompt)} + {max_new_tokens} "
                             f"exceeds slot capacity {self.s_max}")
        rid = self._next_rid
        self._next_rid += 1
        self._pending.append(Request(rid, prompt, max_new_tokens))
        return rid

    def _admit(self) -> List[int]:
        """Move pending requests into free slots (prefill writes the slot's
        cache rows; one prefill compile per prompt length — callers who
        need fewer compiles can pad prompts to buckets themselves).
        Returns rids that finished AT admission (max_new_tokens == 1 or
        EOS on the prefill token)."""
        import paddle_tpu as paddle
        finished = []
        while self._pending and self._free:
            req = self._pending.pop(0)
            slot = self._free.pop(0)
            ids = paddle.to_tensor(req.prompt[None, :])
            with paddle.no_grad():
                logits, cache, _t = self.model.prefill(ids, self.s_max)
            # write the slot: caches[:, :, slot] = cache[:, :, 0]
            self._caches[:, :, slot] = cache[:, :, 0]
            tok = int(self._pick(np.asarray(logits._data)[:, -1])[0])
            req.slot = slot
            req.tokens.append(tok)
            self._slot_req[slot] = req
            self._t[slot, 0] = len(req.prompt)
            self._last_tok[slot, 0] = tok
            if self._maybe_finish(req, tok):
                finished.append(req.rid)
        return finished

    def _maybe_finish(self, req: Request, tok: int) -> bool:
        if (tok == self.eos_id if self.eos_id is not None else False) \
                or len(req.tokens) >= req.max_new_tokens:
            slot = req.slot
            req.slot = None
            del self._slot_req[slot]
            self._free.append(slot)
            self._t[slot, 0] = self.s_max - 1  # park
            self._finished[req.rid] = req
            return True
        return False

    def _pick(self, logits_np):
        """Next-token selection (greedy or sampled) on host logits [B, V];
        shares the model's sampling semantics."""
        from ..models.gpt import GPT2ForCausalLM
        return GPT2ForCausalLM._select_token(
            logits_np, self._do_sample, self._temperature, self._top_k,
            self._top_p, self._rng)

    # -- the engine ---------------------------------------------------------
    def step(self) -> List[int]:
        """Admit, decode one token for every active slot, evict finished.
        Returns the rids that finished during THIS call (including ones
        that finished at admission)."""
        import paddle_tpu as paddle
        finished = self._admit()
        if not self._slot_req:
            return finished
        tok_t = paddle.to_tensor(self._last_tok)
        t_t = paddle.to_tensor(self._t)
        # serving is inference by construction: the batcher supplies the
        # no_grad scope its donating compiled step requires
        with paddle.no_grad():
            logits, self._caches, _ = self._step_fn(tok_t, self._caches,
                                                    t_t)
        next_tok = self._pick(np.asarray(logits._data)[:, -1])
        for slot, req in list(self._slot_req.items()):
            tok = int(next_tok[slot])
            self._t[slot, 0] += 1
            req.tokens.append(tok)
            self._last_tok[slot, 0] = tok
            if self._maybe_finish(req, tok):
                finished.append(req.rid)
        return finished

    def result(self, rid: int) -> np.ndarray:
        """Full sequence (prompt + generated) of a finished request."""
        req = self._finished[rid]
        return np.concatenate([req.prompt, np.asarray(req.tokens)])

    def pop_result(self, rid: int) -> np.ndarray:
        """result() + release the request's memory — long-lived batchers
        must pop (or use run_until_done, which pops) or _finished grows
        with every request ever served."""
        out = self.result(rid)
        del self._finished[rid]
        return out

    def run_until_done(self, max_steps: int = 10000) -> Dict[int, np.ndarray]:
        """Drive until every submitted request completes; returns (and
        releases) exactly THIS run's results. Raises if the step budget
        is exhausted with work still pending/active — a silent partial
        dict would read as lost requests."""
        done: List[int] = []
        for _ in range(max_steps):
            done += self.step()
            if not self._pending and not self._slot_req:
                break
        else:
            raise RuntimeError(
                f"run_until_done: {len(self._pending)} pending / "
                f"{len(self._slot_req)} active requests remain after "
                f"{max_steps} steps")
        return {rid: self.pop_result(rid) for rid in done}

    @property
    def active(self) -> int:
        return len(self._slot_req)
