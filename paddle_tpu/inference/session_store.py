"""Crash-safe, replica-independent session manifests for durable resume.

A paused conversation's KV chain lives in the radix tree as anonymous
tiered residency — enough to survive churn (the session pin keeps the
chain no lower than the last tier) but not replica death or a fleet
rescale, because tier blobs are keyed per-process. This module adds the
missing identity layer: a **session manifest** on shared storage mapping

    session id -> ordered chain hashes + token ids + model identity
                  + last activity

so ANY replica can later resolve a returning session: if its own cache
still holds the chain (same tokens -> same chain hashes), resume rides
tiered promotion; if not, the manifest's token ids are everything needed
for a full re-prefill — token-exact either way under greedy decoding.

Durability contract (the round-6 checkpoint pattern):

  * publish writes ``<sid>.json.tmp`` through
    ``chaos.torn_write_bytes(..., point="kv.session_publish")`` then
    ``os.replace``s it over the final path — a crash mid-publish leaves
    only a ``.tmp`` no reader trusts, and the previous manifest (if any)
    stays sound.
  * the manifest body carries a whole-document crc32 plus one crc32 PER
    block entry (over the block's packed int64 token bytes — the same
    bytes the chain hash consumed), so a reader detects truncation,
    bit-rot, and token/hash drift independently, stdlib-only
    (``tools/session_inspect.py`` audits manifests with no numpy/jax).
  * ``load`` never raises on a bad manifest: every failure mode becomes
    a typed :class:`SessionFinding` (``torn_manifest``, ``unreadable``,
    ``checksum_mismatch``, ``entry_checksum_mismatch``, ``hash_drift``,
    ``model_mismatch``, ``resume_fault``, ``missing``) and a ``None``
    return — the caller's contract is "fall back to full re-prefill".

``kv.session_resume`` is the chaos seam at the top of ``load``: a drill
can fail the manifest read itself and watch the fleet degrade cleanly.
"""
from __future__ import annotations

import json
import os
import re
import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .prefix_cache import chain_hashes

__all__ = ["SessionManifest", "SessionFinding", "SessionStore",
           "model_identity", "MANIFEST_VERSION"]

MANIFEST_VERSION = 1


def model_identity(model) -> str:
    """Stable identity string for resume-compatibility checks: the model
    class plus the config fields that change logits. Two processes
    serving the same architecture/shape agree; a vocab or depth change
    does not. Calibrated cachekv-int8 scales fold into the identity too:
    a chain of int8 pages is only replayable under the SAME scales, so
    calibration drift between pause and resume must conservatively
    degrade to a full re-prefill rather than dequantize with the wrong
    scales."""
    cfg = getattr(model, "config", None)
    if cfg is None:
        return type(model).__name__
    fields = getattr(cfg, "__dict__", None) or {}
    sig = ",".join(f"{k}={fields[k]!r}" for k in sorted(fields)
                   if not k.startswith("_"))
    h = zlib.crc32(sig.encode()) & 0xFFFFFFFF
    scales = getattr(model, "_cachekv_scales", None)
    if scales is not None:
        import numpy as _np  # local: keep the module header stdlib-only
        q = 0
        for layer in scales:
            for k in sorted(layer):
                q = zlib.crc32(
                    _np.ascontiguousarray(
                        _np.asarray(layer[k], _np.float32)).tobytes(), q)
        return f"{type(model).__name__}:{h:08x}:q{q & 0xFFFFFFFF:08x}"
    return f"{type(model).__name__}:{h:08x}"


def _pack_tokens(tokens) -> bytes:
    """Packed little-endian int64 token bytes — byte-identical to
    ``np.asarray(tokens, np.int64).tobytes()`` without needing numpy, so
    the offline inspector can recompute every CRC and chain hash."""
    return b"".join(struct.pack("<q", int(t)) for t in tokens)


@dataclass
class SessionManifest:
    """One durable session: everything a stranger replica needs to
    resume it (tokens for re-prefill, chain hashes for cache matching,
    model identity for compatibility, last activity for GC policy)."""

    session_id: str
    token_ids: List[int]
    block_size: int
    chain: List[int] = field(default_factory=list)  # ordered chain hashes
    model: str = ""
    last_activity: float = 0.0

    def __post_init__(self):
        self.token_ids = [int(t) for t in self.token_ids]
        if not self.chain:
            self.chain = chain_hashes(self.token_ids, self.block_size)

    @property
    def n_blocks(self) -> int:
        return len(self.chain)

    @property
    def covered_tokens(self) -> int:
        """Tokens whose KV a cached chain can supply (full blocks)."""
        return self.n_blocks * self.block_size


@dataclass
class SessionFinding:
    """A typed manifest problem: what broke, on which session, and why —
    the session analogue of the fleet's remediation findings."""

    kind: str          # torn_manifest | unreadable | checksum_mismatch |
    #                    entry_checksum_mismatch | hash_drift |
    #                    model_mismatch | resume_fault | missing |
    #                    publish_torn
    session_id: str
    path: str
    detail: str = ""


def _metrics():
    from ..observability.metrics import get_registry
    reg = get_registry()
    return (reg.counter("session.published",
                        "session manifests atomically published"),
            reg.counter("session.publish_failures",
                        "manifest publishes that failed (torn write/IO)"),
            reg.counter("session.resumed",
                        "sessions resolved from a sound manifest"),
            reg.counter("session.manifest_corrupt",
                        "manifest loads rejected (torn/corrupt/mismatch)"))


class SessionStore:
    """Filesystem-backed manifest store. ``root`` is the shared volume
    every replica and gateway can reach; the store itself is stateless
    beyond a findings journal, so any number of processes can share one
    root (publishes are atomic whole-file replaces)."""

    def __init__(self, root: str):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self.findings: List[SessionFinding] = []
        (self._published_c, self._publish_fail_c,
         self._resumed_c, self._corrupt_c) = _metrics()

    # -- paths ---------------------------------------------------------------
    def path_for(self, session_id: str) -> str:
        """Human-readable but collision-safe filename: sanitized id plus
        a crc of the raw id (two ids differing only in stripped chars
        cannot alias)."""
        safe = re.sub(r"[^A-Za-z0-9._-]", "_", session_id)[:80]
        tag = zlib.crc32(session_id.encode()) & 0xFFFFFFFF
        return os.path.join(self.root, f"{safe}.{tag:08x}.json")

    def sessions(self) -> List[str]:
        """Session ids with a published (non-tmp) manifest."""
        out = []
        for name in sorted(os.listdir(self.root)):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    out.append(json.load(f)["session_id"])
            except (OSError, ValueError, KeyError):
                continue
        return out

    # -- serialization -------------------------------------------------------
    @staticmethod
    def _encode(m: SessionManifest) -> bytes:
        blocks = []
        for i, h in enumerate(m.chain):
            blk = m.token_ids[i * m.block_size:(i + 1) * m.block_size]
            blocks.append({"h": f"{h:016x}",
                           "crc": zlib.crc32(_pack_tokens(blk)) & 0xFFFFFFFF})
        body = {"version": MANIFEST_VERSION,
                "session_id": m.session_id,
                "model": m.model,
                "block_size": m.block_size,
                "last_activity": m.last_activity,
                "n_tokens": len(m.token_ids),
                "tokens": m.token_ids,
                "blocks": blocks}
        body["crc"] = zlib.crc32(
            json.dumps(body, sort_keys=True).encode()) & 0xFFFFFFFF
        return json.dumps(body, sort_keys=True).encode()

    def _find(self, kind: str, sid: str, path: str, detail: str = ""):
        f = SessionFinding(kind, sid, path, detail)
        self.findings.append(f)
        # field name is ``finding`` (not ``kind``): the spool reserves
        # ``kind`` for its record-type tag and **fields would clobber it,
        # making the record invisible to the fleet aggregator
        self._spool("finding", session=sid, finding=kind, detail=detail)
        return f

    @staticmethod
    def _spool(op: str, **fields):
        from ..observability.fleet import spool_event
        spool_event("session", op=op, **fields)

    # -- the durable API -----------------------------------------------------
    def publish(self, m: SessionManifest) -> bool:
        """Atomically publish/overwrite ``m``. False (plus a typed
        finding and a counter) on a torn write or IO error — the on-disk
        state is then either absent or the PREVIOUS sound manifest."""
        if not m.last_activity:
            m.last_activity = time.time()
        fpath = self.path_for(m.session_id)
        tmp = fpath + ".tmp"
        from ..resilience.chaos import torn_write_bytes
        try:
            torn_write_bytes(tmp, self._encode(m),
                             point="kv.session_publish")
            os.replace(tmp, fpath)
        except Exception as e:  # noqa: BLE001 — chaos/IO surface as finding
            self._publish_fail_c.inc()
            self._find("publish_torn", m.session_id, tmp, repr(e))
            return False
        self._published_c.inc()
        self._spool("publish", session=m.session_id,
                    blocks=m.n_blocks, tokens=len(m.token_ids))
        return True

    def load(self, session_id: str,
             expect_model: Optional[str] = None) -> Optional[SessionManifest]:
        """Resolve a session id to a validated manifest, or ``None`` with
        a typed finding. Fires the ``kv.session_resume`` chaos seam; an
        injected fault degrades to ``None`` (callers full-prefill)."""
        fpath = self.path_for(session_id)
        from ..resilience.chaos import fault_point
        try:
            fault_point("kv.session_resume")
        except Exception as e:  # noqa: BLE001 — injected resume fault
            self._corrupt_c.inc()
            self._find("resume_fault", session_id, fpath, repr(e))
            return None
        if not os.path.exists(fpath):
            kind = ("torn_manifest" if os.path.exists(fpath + ".tmp")
                    else "missing")
            self._find(kind, session_id, fpath,
                       "only a .tmp exists (publish crashed mid-write)"
                       if kind == "torn_manifest" else "no manifest")
            if kind == "torn_manifest":
                self._corrupt_c.inc()
            return None
        try:
            raw = open(fpath, "rb").read()
            doc = json.loads(raw)
        except (OSError, ValueError) as e:
            self._corrupt_c.inc()
            self._find("unreadable", session_id, fpath, repr(e))
            return None
        body = {k: v for k, v in doc.items() if k != "crc"}
        want = zlib.crc32(
            json.dumps(body, sort_keys=True).encode()) & 0xFFFFFFFF
        if doc.get("crc") != want:
            self._corrupt_c.inc()
            self._find("checksum_mismatch", session_id, fpath,
                       f"document crc {doc.get('crc')} != {want}")
            return None
        tokens = doc.get("tokens", [])
        bs = int(doc.get("block_size", 0) or 0)
        if bs < 1 or len(tokens) != doc.get("n_tokens"):
            self._corrupt_c.inc()
            self._find("checksum_mismatch", session_id, fpath,
                       "token count / block size fields inconsistent")
            return None
        chain = chain_hashes(tokens, bs)
        entries = doc.get("blocks", [])
        if len(entries) != len(chain):
            self._corrupt_c.inc()
            self._find("hash_drift", session_id, fpath,
                       f"{len(entries)} entries != {len(chain)} full blocks")
            return None
        for i, (h, entry) in enumerate(zip(chain, entries)):
            blk = tokens[i * bs:(i + 1) * bs]
            crc = zlib.crc32(_pack_tokens(blk)) & 0xFFFFFFFF
            if entry.get("crc") != crc:
                self._corrupt_c.inc()
                self._find("entry_checksum_mismatch", session_id, fpath,
                           f"block {i} crc {entry.get('crc')} != {crc}")
                return None
            if entry.get("h") != f"{h:016x}":
                self._corrupt_c.inc()
                self._find("hash_drift", session_id, fpath,
                           f"block {i} hash {entry.get('h')} != {h:016x}")
                return None
        if expect_model and doc.get("model") and doc["model"] != expect_model:
            self._find("model_mismatch", session_id, fpath,
                       f"manifest model {doc['model']!r} != "
                       f"{expect_model!r}")
            return None
        m = SessionManifest(session_id=doc["session_id"], token_ids=tokens,
                            block_size=bs, chain=chain,
                            model=doc.get("model", ""),
                            last_activity=float(
                                doc.get("last_activity", 0.0)))
        self._resumed_c.inc()
        self._spool("load", session=session_id, blocks=m.n_blocks,
                    tokens=len(tokens))
        return m

    def delete(self, session_id: str) -> bool:
        fpath = self.path_for(session_id)
        removed = False
        for p in (fpath, fpath + ".tmp"):
            try:
                os.unlink(p)
                removed = True
            except OSError:
                pass
        if removed:
            self._spool("delete", session=session_id)
        return removed

    def drain_findings(self) -> List[SessionFinding]:
        out, self.findings = self.findings, []
        return out
