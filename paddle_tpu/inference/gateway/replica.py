"""Replica lifecycle: N batchers behind one gateway.

A ``Replica`` wraps one ``ContinuousBatcher``/``PagedContinuousBatcher``
(anything derived from ``_BatcherBase``) with pool metadata: routing
weight, warm prompt-bucket set (affinity state), draining flag, and
liveness. Its health surface IS the batcher's own
``resilience.recovery.HealthStateMachine`` — the pool never invents a
second state machine.

``ReplicaPool`` owns add/drain/remove and the failure policy: each
replica's step runs under a shared ``resilience.retry.RetryPolicy``, so
transient faults (chaos ``serving.step`` injections, flaky dispatch)
retry in place; when the policy gives up — or the step raises something
non-retryable — the replica is declared DEAD and the gateway requeues
its in-flight requests onto the survivors (counted ``gateway.requeued``;
greedy decode makes the resumed continuation token-exact, the same
contract the paged batcher's preemption path relies on).
"""
from __future__ import annotations

import time as _time
from typing import Dict, List, Optional, Set, Tuple

from ...resilience.recovery import HealthState
from ...resilience.retry import RetryGiveUp, RetryPolicy

__all__ = ["Replica", "ReplicaPool"]


def _pool_metrics():
    from ...observability.metrics import get_registry
    reg = get_registry()
    return (reg.gauge("gateway.replicas_alive",
                      "live (non-dead) replicas in the pool"),
            reg.counter("gateway.replica_deaths",
                        "replicas declared dead after step failures",
                        labelnames=("replica",)))


def _step_seconds_h():
    from ...observability.metrics import get_registry
    return get_registry().histogram(
        "gateway.replica.step_seconds",
        "wall time of one replica engine step (incl. retries)",
        labelnames=("replica",))


def _member_step_seconds_h():
    # per-shard-member view of the same step: a tensor-parallel group
    # used to show up as one opaque replica — this names the mesh
    # members that actually held chips for the step (member == replica
    # name for a plain single-engine replica)
    from ...observability.metrics import get_registry
    return get_registry().histogram(
        "replica.step_seconds",
        "wall time of one engine step per shard-group member",
        labelnames=("replica", "member"))


class Replica:
    """One serving engine in the pool."""

    def __init__(self, name: str, batcher, weight: float = 1.0):
        if weight <= 0:
            raise ValueError(f"replica weight must be positive, "
                             f"got {weight}")
        self.name = name
        self.batcher = batcher
        self.weight = float(weight)
        self.draining = False
        self.alive = True
        # prompt-bucket rungs this replica has prefilled before — the
        # affinity policy's proxy for "compile cache is warm here"
        self.warm_buckets: Set[int] = set()

    @property
    def shard_group(self):
        """The batcher's tensor-parallel ``distributed.mesh.ShardGroup``
        when it serves as one logical TP replica (weights/KV split over
        the mesh's tensor axis), else None. A member death there raises
        the non-retryable TPMemberDied from the batcher's step — the
        pool's ordinary fatal path declares the WHOLE group dead."""
        return getattr(self.batcher, "shard_group", None)

    def describe(self) -> Dict[str, object]:
        d: Dict[str, object] = {
            "name": self.name, "alive": self.alive,
            "draining": self.draining, "load": self.load,
        }
        group = self.shard_group
        if group is not None:
            d["shard_group"] = group.describe()
        return d

    # -- the KV-aware routing surface -----------------------------------------
    def prefix_summary(self) -> Optional[Dict[str, object]]:
        """Hashed radix-tree advertisement for KV-aware routing
        (``{"block_size": B, "hashes": {chain_hash: depth}}``), or None
        when the batcher runs without a prefix cache. In a multi-process
        deployment this is the payload a replica would gossip to the
        gateway; in-process the router just reads it live."""
        cache = getattr(self.batcher, "prefix_cache", None)
        return cache.summary() if cache is not None else None

    # -- load/capacity the router reads --------------------------------------
    @property
    def load(self) -> int:
        """In-flight request count: queued + active (+ mid-admission)."""
        b = self.batcher
        return (b.active + b.pending
                + (1 if getattr(b, "_admitting", None) else 0))

    @property
    def free_slots(self) -> int:
        """Slots the batcher could still fill — the dispatch gate. The
        gateway holds excess work in ITS queue (where priorities and
        requeues still apply) instead of burying it in a replica FIFO."""
        return max(0, self.batcher.max_batch - self.load)

    @property
    def health(self):
        return self.batcher.health

    def routable(self) -> bool:
        """Eligible for NEW work: live, not draining, not UNREADY.
        (STARTING counts — a fresh replica has to get its first request
        from somewhere.)"""
        return (self.alive and not self.draining
                and self.health.state != HealthState.UNREADY)

    def __repr__(self):
        group = self.shard_group
        tp = (f", tp={group.name}x{group.degree}"
              if group is not None else "")
        return (f"Replica({self.name!r}, load={self.load}, "
                f"alive={self.alive}, draining={self.draining}{tp})")


class ReplicaPool:
    """Ordered replica set + the step/failure policy."""

    def __init__(self, step_retry: Optional[RetryPolicy] = None):
        # zero-sleep default: transient chaos faults retry immediately;
        # give-up after 3 attempts declares the replica dead
        self.step_retry = step_retry or RetryPolicy(
            max_attempts=3, base_delay=0.0, jitter=0.0, seed=0)
        self._replicas: Dict[str, Replica] = {}

    # -- lifecycle ------------------------------------------------------------
    def add(self, name: str, batcher, weight: float = 1.0) -> Replica:
        if name in self._replicas:
            raise ValueError(f"replica {name!r} already in the pool")
        rep = Replica(name, batcher, weight=weight)
        self._replicas[name] = rep
        alive_g, _ = _pool_metrics()
        alive_g.set(len(self.live()))
        return rep

    def get(self, name: str) -> Replica:
        return self._replicas[name]

    def __contains__(self, name: str) -> bool:
        return name in self._replicas

    def __len__(self) -> int:
        return len(self._replicas)

    def replicas(self) -> List[Replica]:
        return list(self._replicas.values())

    def live(self) -> List[Replica]:
        """Replicas that still step (draining ones keep stepping — they
        have in-flight work to finish)."""
        return [r for r in self._replicas.values() if r.alive]

    def routable(self) -> List[Replica]:
        return [r for r in self._replicas.values() if r.routable()]

    def drain(self, name: str):
        """Stop routing new work to ``name``; in-flight work finishes.
        The batcher's health machine advertises UNREADY so external
        probes agree with the pool."""
        rep = self._replicas[name]
        rep.draining = True
        rep.health.drain()

    def remove(self, name: str, force: bool = False) -> Replica:
        """Remove a drained/empty replica. With in-flight work, refuse
        unless ``force`` — the GATEWAY must requeue those requests first
        (it owns the request bookkeeping)."""
        rep = self._replicas[name]
        if rep.alive and rep.load > 0 and not force:
            raise RuntimeError(
                f"replica {name!r} still has {rep.load} in-flight "
                f"request(s); drain it first or pass force=True")
        del self._replicas[name]
        alive_g, _ = _pool_metrics()
        alive_g.set(len(self.live()))
        return rep

    # -- the step/failure policy ----------------------------------------------
    def step_replica(self, rep: Replica) -> Tuple[str, object]:
        """One engine step under the retry policy.

        Returns ``("ok", finished_rids)`` or ``("dead", exc)`` — the
        latter after marking the replica dead (health drained, gauges
        updated). The caller requeues the dead replica's requests.
        """
        t0 = _time.perf_counter()
        try:
            # per-replica chaos seam: the shared ``serving.step`` point
            # fires on whichever replica steps next, so a drill that
            # needs to straggle ONE replica arms this name instead
            # (e.g. ``gateway.step.r1:delay:delay_s=0.05``). An error
            # kind here bypasses the retry policy — it models the
            # replica's host dying, not a flaky step
            from ...resilience.chaos import fault_point
            fault_point(f"gateway.step.{rep.name}")
            rids = self.step_retry.call(rep.batcher.step,
                                        point=f"gateway.step.{rep.name}")
            elapsed = _time.perf_counter() - t0
            _step_seconds_h().labels(replica=rep.name).observe(elapsed)
            group = rep.shard_group
            members = ([m for m in group.members
                        if m not in group.failed_members]
                       if group is not None else [rep.name])
            mh = _member_step_seconds_h()
            for member in members:
                mh.labels(replica=rep.name, member=member).observe(elapsed)
            return "ok", rids
        except RetryGiveUp as exc:
            self._kill(rep)
            return "dead", exc
        except Exception as exc:  # noqa: BLE001 — non-retryable = fatal
            self._kill(rep)
            return "dead", exc

    def _kill(self, rep: Replica):
        rep.alive = False
        rep.health.drain()
        alive_g, deaths_c = _pool_metrics()
        alive_g.set(len(self.live()))
        deaths_c.labels(replica=rep.name).inc()
