"""Serving control plane: multi-replica gateway over the batchers.

The single-process batchers (``paddle_tpu.inference.serving``) stop at
one engine; this package is the layer above — a deterministic,
single-threaded control plane that:

  * pools N batcher replicas (``ReplicaPool``/``Replica``) with health
    integration, drain/remove lifecycle, and a retry-then-declare-dead
    step policy compatible with ``resilience.chaos`` injection;
  * routes requests through pluggable policies (least-loaded,
    session/prefix-bucket affinity, smooth weighted round-robin) behind
    per-tenant token-bucket quotas and a two-level priority queue with
    SLO-aware admission (deadline feasibility, typed
    ``Overloaded``/``DeadlineExceeded`` rejections);
  * streams tokens to callers (``StreamingSession``) with intake
    backpressure;
  * requeues in-flight requests off dead replicas token-exactly
    (``gateway.requeued``), instrumented end-to-end through
    ``paddle_tpu.observability`` (``gateway.*`` series).

Entry point::

    gw = Gateway(policy="affinity", max_queue_depth=64)
    gw.add_replica("r0", ContinuousBatcher(model))
    gw.add_replica("r1", ContinuousBatcher(model))
    gid = gw.submit(prompt_ids, max_new_tokens=32, tenant="alice")
    out = gw.run_until_done()[gid]
"""
from .autoscaler import Autoscaler
from .gateway import Gateway, GatewayRequest
from .quota import TenantQuotas, TokenBucket
from .replica import Replica, ReplicaPool
from .router import (DispatchQueue, LeastLoadedPolicy, PRIORITY_HIGH,
                     PRIORITY_LOW, RoutePolicy, SessionAffinityPolicy,
                     WeightedRoundRobinPolicy, resolve_policy)
from .streaming import StreamingSession

__all__ = [
    "Gateway", "GatewayRequest", "Autoscaler",
    "TokenBucket", "TenantQuotas",
    "Replica", "ReplicaPool",
    "RoutePolicy", "LeastLoadedPolicy", "SessionAffinityPolicy",
    "WeightedRoundRobinPolicy", "resolve_policy", "DispatchQueue",
    "PRIORITY_HIGH", "PRIORITY_LOW",
    "StreamingSession",
]
