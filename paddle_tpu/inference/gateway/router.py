"""Request routing: placement policies + the two-level dispatch queue.

Placement policies pick a replica for each dispatchable request:

  * ``least_loaded``      — fewest in-flight requests (queued + active);
    the goodput-oriented default (DistServe/Splitwise-style placement
    degenerates to this when every replica runs the same phase mix).
  * ``affinity``          — KV-aware placement first (the replica whose
    radix prefix cache advertises the deepest cached prefix of this
    prompt wins — shared-prefix prefill becomes a page lookup there),
    then session stickiness (follow-up turns land on the replica
    holding the warm KV/compile state), then prompt-BUCKET warmth (a
    replica that already compiled this ``perf.buckets`` prefill rung is
    preferred — route to the warm executable, not a cold one), falling
    back to least-loaded.
  * ``weighted_rr``       — smooth weighted round-robin over replica
    weights (heterogeneous pools: a 2x-capacity replica takes 2x the
    requests).

Routing decisions are instrumented: ``gateway.route.prefix_hit`` when a
cached-prefix match carried the decision, ``gateway.route.affinity_hit``
when a session/bucket match did, ``gateway.route.fallback`` when the
affinity policy had to fall back.

The dispatch queue is TWO-LEVEL (interactive=0 above batch=1) with an
anti-starvation share: every ``low_share``-th dispatch serves the low
queue first, so a saturating stream of high-priority work cannot starve
batch tenants (the acceptance bar: the low-priority tenant still
completes under mixed load).
"""
from __future__ import annotations

import time as _time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["RoutePolicy", "LeastLoadedPolicy", "SessionAffinityPolicy",
           "WeightedRoundRobinPolicy", "resolve_policy", "DispatchQueue",
           "PRIORITY_HIGH", "PRIORITY_LOW"]

PRIORITY_HIGH = 0
PRIORITY_LOW = 1


def _route_metrics():
    from ...observability.metrics import get_registry
    reg = get_registry()
    return (reg.counter("gateway.route.affinity_hit",
                        "dispatches placed by session/bucket affinity"),
            reg.counter("gateway.route.fallback",
                        "affinity dispatches that fell back to "
                        "least-loaded"),
            reg.counter("gateway.route.prefix_hit",
                        "dispatches placed on the replica advertising "
                        "the deepest cached prompt prefix"),
            reg.counter("gateway.route.session_resume",
                        "returning sessions whose sticky replica was "
                        "gone, resolved to a new replica (prefix depth "
                        "or fallback)"))


def _queue_wait_h():
    from ...observability.metrics import get_registry
    return get_registry().histogram(
        "gateway.queue_wait_seconds",
        "gateway-queue residency from submit to dispatch pop",
        labelnames=("lane",))


class RoutePolicy:
    """Pick one replica from the routable candidates for a request."""

    name = "base"

    def select(self, req, candidates: Sequence):
        raise NotImplementedError

    def on_dispatch(self, req, replica):
        """Observe a completed placement (update affinity state)."""


class LeastLoadedPolicy(RoutePolicy):
    name = "least_loaded"

    def select(self, req, candidates: Sequence):
        # (load, name): deterministic tie-break by name
        return min(candidates, key=lambda r: (r.load, r.name))


class WeightedRoundRobinPolicy(RoutePolicy):
    """Smooth WRR (nginx-style): each pick adds weight to every
    candidate's running credit and the winner pays back the total, so a
    weight-2 replica lands 2 of every 3 dispatches without bursts."""

    name = "weighted_rr"

    def __init__(self):
        self._credit: Dict[str, float] = {}

    def select(self, req, candidates: Sequence):
        total = 0.0
        for r in candidates:
            self._credit[r.name] = self._credit.get(r.name, 0.0) + r.weight
            total += r.weight
        # deterministic: max credit, name tie-break
        best = max(candidates,
                   key=lambda r: (self._credit[r.name], r.name))
        self._credit[best.name] -= total
        return best


class SessionAffinityPolicy(RoutePolicy):
    """Cached-prefix depth, then session stickiness, then prompt-bucket
    warmth, then fallback.

    KV-aware placement comes FIRST: replicas running a radix prefix
    cache advertise hashed chain summaries (``Replica.prefix_summary``),
    and the policy computes the request prompt's own chain hashes
    (``inference.prefix_cache.chain_hashes``) to find the replica that
    already holds the deepest prefix of this prompt — landing there
    turns the shared-system-prompt prefill into a page-table lookup,
    which dominates any compile-cache warmth. Ties break by (load,
    name); hits count ``gateway.route.prefix_hit``.

    Then the classic tiers: a follow-up turn (same ``session_id``)
    routes to the replica that served the session before — its paged KV
    pages and compiled prefill signatures for the conversation are warm.
    Requests without a sticky session prefer a replica whose compile
    cache already holds the prompt's ``perf.buckets`` rung
    (``Replica.warm_buckets``, recorded at dispatch). Both count
    ``gateway.route.affinity_hit``; a miss counts
    ``gateway.route.fallback`` and defers to the fallback policy.
    """

    name = "affinity"

    def __init__(self, fallback: Optional[RoutePolicy] = None):
        self.fallback = fallback or LeastLoadedPolicy()
        self._sessions: Dict[str, str] = {}     # session_id -> replica name

    @staticmethod
    def _prefix_tokens(req, summary,
                       chains: Dict[int, List[int]]) -> Tuple[int, int]:
        """(total cached tokens, device-resident tokens) of ``req.prompt``
        per ``summary``. ``chains`` memoizes the prompt's chain hashes per
        block size so an N-replica pool hashes the prompt once, not N
        times. Tiered replicas advertise per-hash residency under
        ``"tiers"``; summaries without it (untiered, or a pre-tier
        replica) count everything as device-resident."""
        bs = summary.get("block_size")
        hashes = summary.get("hashes")
        if not bs or not hashes:
            return 0, 0
        chain = chains.get(bs)
        if chain is None:
            from ..prefix_cache import chain_hashes
            prompt = getattr(req, "prompt", None)
            chain = (chain_hashes(prompt, bs)
                     if prompt is not None else [])
            chains[bs] = chain
        tiers = summary.get("tiers") or {}
        depth = dev_depth = 0
        for h in chain:
            # chained hashing: a depth-d node implies its whole ancestor
            # chain, so the first miss ends the longest common prefix
            if h not in hashes:
                break
            depth += 1
            # device depth only grows while contiguous from the root
            # (residency is monotone down the chain, so the first
            # off-device block ends it)
            if dev_depth == depth - 1 and tiers.get(h, "device") == "device":
                dev_depth = depth
        return depth * bs, dev_depth * bs

    def select(self, req, candidates: Sequence):
        hit_c, fb_c, px_c, sr_c = _route_metrics()
        sid = getattr(req, "session_id", None)
        # a RESUMED session whose sticky replica vanished (death,
        # rescale) resolves like any other request — prefix depth finds
        # a survivor holding the chain, else fallback full-prefills —
        # but the resolution is counted: it's the durable-resume path
        orphan_session = (sid is not None
                          and getattr(req, "resumed", False)
                          and self._sessions.get(sid) is None)
        chains: Dict[int, List[int]] = {}
        best, best_key = None, (0, 0)
        for r in candidates:
            summary = getattr(r, "prefix_summary", lambda: None)()
            if not summary:
                continue
            key = self._prefix_tokens(req, summary, chains)
            # deepest total match first (a host-resident block beats a
            # recompute — promotion is a memcpy, prefill is flops), then
            # prefer the replica holding more of it ON DEVICE
            if key > best_key or (key == best_key and key[0] > 0 and
                                  (r.load, r.name) <
                                  (best.load, best.name)):
                best, best_key = r, key
        if best_key[0] > 0:
            px_c.inc()
            if orphan_session:
                sr_c.inc()
            return best
        by_name = {r.name: r for r in candidates}
        if sid is not None and self._sessions.get(sid) in by_name:
            hit_c.inc()
            return by_name[self._sessions[sid]]
        bucket = getattr(req, "bucket", None)
        if bucket is not None:
            warm = [r for r in candidates if bucket in r.warm_buckets]
            if warm:
                hit_c.inc()
                if orphan_session:
                    sr_c.inc()
                return min(warm, key=lambda r: (r.load, r.name))
        fb_c.inc()
        if orphan_session:
            sr_c.inc()
        return self.fallback.select(req, candidates)

    def on_dispatch(self, req, replica):
        sid = getattr(req, "session_id", None)
        if sid is not None:
            self._sessions[sid] = replica.name
        bucket = getattr(req, "bucket", None)
        if bucket is not None:
            replica.warm_buckets.add(bucket)

    def forget_replica(self, name: str):
        """Drop sticky sessions pointing at a dead/removed replica so
        their next turn re-routes instead of falling through the
        candidate filter forever."""
        for sid in [s for s, n in self._sessions.items() if n == name]:
            del self._sessions[sid]

    def forget_session(self, session_id: str):
        """Drop one session's stickiness (released/expired sessions must
        not keep steering traffic at their old replica)."""
        self._sessions.pop(session_id, None)


_POLICIES = {
    "least_loaded": LeastLoadedPolicy,
    "affinity": SessionAffinityPolicy,
    "weighted_rr": WeightedRoundRobinPolicy,
}


def resolve_policy(spec) -> RoutePolicy:
    """Normalize the policy specs the gateway accepts: a name, a
    RoutePolicy instance, or None (-> least_loaded)."""
    if spec is None:
        return LeastLoadedPolicy()
    if isinstance(spec, RoutePolicy):
        return spec
    if isinstance(spec, str):
        cls = _POLICIES.get(spec.strip().lower())
        if cls is None:
            raise ValueError(f"unknown routing policy {spec!r} "
                             f"(one of {sorted(_POLICIES)})")
        return cls()
    raise ValueError(f"bad routing policy spec {spec!r}")


class DispatchQueue:
    """Two FIFO lanes (high above low) with a guaranteed low-lane share.

    ``low_share=K`` means every K-th dispatch serves the low lane first
    (when it has work); K=0 disables the share (strict priority). Counts
    are deterministic — no clocks, no randomness — so scheduling replays
    exactly in tests.
    """

    def __init__(self, low_share: int = 4):
        if low_share < 0:
            raise ValueError("low_share must be >= 0")
        self.low_share = low_share
        self._lanes = (deque(), deque())
        self._dispatched = 0

    def push(self, req):
        self._lanes[req.priority].append(req)

    def push_front(self, req):
        """Requeue (replica death, failed dispatch): back to the HEAD of
        its lane, preserving arrival order among its peers."""
        self._lanes[req.priority].appendleft(req)

    def __len__(self):
        return len(self._lanes[0]) + len(self._lanes[1])

    def _lane_order(self):
        if self.low_share and self._lanes[PRIORITY_LOW] and \
                (self._dispatched + 1) % self.low_share == 0:
            return (PRIORITY_LOW, PRIORITY_HIGH)
        return (PRIORITY_HIGH, PRIORITY_LOW)

    def peek(self):
        for lane in self._lane_order():
            if self._lanes[lane]:
                return self._lanes[lane][0]
        return None

    def pop(self):
        for lane in self._lane_order():
            if self._lanes[lane]:
                self._dispatched += 1
                req = self._lanes[lane].popleft()
                submit_t = getattr(req, "submit_t", None)
                if submit_t:
                    _queue_wait_h().labels(
                        lane="high" if lane == PRIORITY_HIGH
                        else "low").observe(
                        max(0.0, _time.perf_counter() - submit_t))
                return req
        return None

    def remove(self, req) -> bool:
        try:
            self._lanes[req.priority].remove(req)
            return True
        except ValueError:
            return False

    def drain(self) -> List:
        """Empty both lanes (gateway shutdown), high lane first."""
        out = list(self._lanes[0]) + list(self._lanes[1])
        self._lanes[0].clear()
        self._lanes[1].clear()
        return out
