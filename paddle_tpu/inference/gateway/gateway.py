"""The serving control plane: one gateway over N batcher replicas.

The layer the single-process batchers stop at: a ``Gateway`` owns a
``ReplicaPool`` of ``ContinuousBatcher``/``PagedContinuousBatcher``
replicas, an SLO-aware admission front door (tenant token-bucket quotas,
two-level priority queue with an anti-starvation share, deadline
feasibility), a pluggable ``Router`` (least-loaded / session+bucket
affinity / weighted round-robin), and ``StreamingSession`` delivery.

Control flow is single-threaded and deterministic — ``step()`` advances
the whole plane one tick (expire, dispatch, step every live replica,
poll tokens, harvest) — so an N-replica deployment simulates exactly in
tests with no multiprocessing. The same loop shape drives a real
deployment where each replica's step dispatches one compiled decode on
its own chip set.

Failure policy: a replica whose step exhausts the pool's
``resilience.retry`` policy (or raises non-retryably) is declared dead;
its in-flight requests requeue at the head of the gateway queue
(``gateway.requeued``) and resume on survivors from
``prompt ⧺ delivered`` — token-exact under greedy decoding, the same
recompute contract the paged batcher's preemption path uses. Sampled
requests resume too, but their continuation re-draws (document, not a
bug: exactness needs a deterministic decoder).

Typed rejections reuse the batchers' exception family
(``resilience.recovery.Overloaded`` / ``DeadlineExceeded``): quota and
queue-capacity sheds raise ``Overloaded``; infeasible or expired
deadlines raise ``DeadlineExceeded``. One family, every serving layer.
"""
from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ...observability import trace_context as _trace
from ...resilience.recovery import DeadlineExceeded, Overloaded
from ...perf.buckets import resolve_ladder
from .quota import TenantQuotas, TokenBucket
from .replica import Replica, ReplicaPool
from .router import (DispatchQueue, PRIORITY_HIGH, PRIORITY_LOW,
                     SessionAffinityPolicy, resolve_policy)
from .streaming import StreamingSession

__all__ = ["Gateway", "GatewayRequest"]

_PRIORITIES = {"high": PRIORITY_HIGH, "interactive": PRIORITY_HIGH,
               "low": PRIORITY_LOW, "batch": PRIORITY_LOW,
               PRIORITY_HIGH: PRIORITY_HIGH, PRIORITY_LOW: PRIORITY_LOW}


@dataclass
class GatewayRequest:
    """One request's gateway-side lifecycle record."""

    gid: int
    tenant: str
    prompt: np.ndarray              # [s] int64 — the ORIGINAL prompt
    max_new_tokens: int
    priority: int
    session_id: Optional[str] = None
    resumed: bool = False           # came back via resume_session
    bucket: Optional[int] = None    # perf.buckets rung (affinity key)
    submit_t: float = 0.0
    deadline_t: Optional[float] = None
    delivered: List[int] = field(default_factory=list)
    attempts: int = 0               # dispatch attempts (requeues)
    replica: Optional[str] = None   # current assignment
    rid: Optional[int] = None       # batcher-side request id
    _consumed: int = 0              # tokens read from the CURRENT rid
    finished: bool = False
    failure: Optional[Exception] = None
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    trace: Optional[object] = None  # observability.TraceContext
    spans: Dict[str, object] = field(default_factory=dict)

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.delivered)


class _GatewayStats:
    """Local counters for ``stats()`` + the process-wide ``gateway.*``
    registry series (the pattern ``_ServingStats`` set)."""

    def __init__(self):
        from ...observability.metrics import get_registry
        reg = get_registry()
        self.requests_c = reg.counter(
            "gateway.requests", "requests accepted at the gateway")
        self.dispatch_c = reg.counter(
            "gateway.dispatches", "request placements onto replicas")
        self.completions_c = reg.counter(
            "gateway.completions", "requests finished across the pool")
        self.requeued_c = reg.counter(
            "gateway.requeued",
            "in-flight requests requeued off a dead/removed replica")
        self.shed_c = reg.counter(
            "gateway.shed", "requests rejected: gateway queue at capacity")
        self.tenant_shed_c = reg.counter(
            "gateway.tenant_shed", "requests rejected by tenant quota",
            labelnames=("tenant",))
        self.infeasible_c = reg.counter(
            "gateway.infeasible",
            "requests rejected: deadline infeasible at admission")
        self.expired_c = reg.counter(
            "gateway.deadline_expired",
            "requests abandoned on an expired deadline")
        self.failures_c = reg.counter(
            "gateway.failures", "requests failed (non-deadline)")
        self.tokens_c = reg.counter(
            "gateway.tokens", "tokens delivered to callers")
        self.queue_depth_g = reg.gauge(
            "gateway.queue_depth", "requests waiting in the gateway queue")
        self.inflight_g = reg.gauge(
            "gateway.inflight", "requests placed on replicas right now")
        self.ttft_h = reg.histogram(
            "gateway.ttft_seconds", "gateway submit to first token")
        self.ttft_rung_h = reg.histogram(
            "gateway.ttft_seconds_by_rung",
            "gateway submit to first token, by resolved prompt rung",
            labelnames=("rung",))
        self.tpot_h = reg.histogram(
            "gateway.tpot_seconds", "per-token latency after the first")
        self.reset()

    def reset(self):
        self.requests = 0
        self.completions = 0
        self.requeued = 0
        self.shed = 0
        self.infeasible = 0
        self.expired = 0
        self.failures = 0
        self.tokens = 0
        self.t0 = _time.perf_counter()


class Gateway:
    """Multi-replica serving front door. See the module docstring.

    policy: routing policy spec (``"least_loaded"``, ``"affinity"``,
    ``"weighted_rr"``, or a ``RoutePolicy``). quotas: ``TenantQuotas``
    or a ``{tenant: TokenBucket}`` dict. max_queue_depth: gateway-queue
    shed threshold. low_share: every K-th dispatch serves the low lane
    (anti-starvation). max_request_attempts: dispatches per request
    before a requeue storm fails it. slo_tpot_s / slo_ttft_s: seed the
    deadline-feasibility estimate (later refined by a completion-time
    EWMA); with no estimate the check is skipped.
    """

    def __init__(self, policy="least_loaded", quotas=None,
                 max_queue_depth: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 low_share: int = 4, max_request_attempts: int = 3,
                 step_retry=None, slo_tpot_s: Optional[float] = None,
                 slo_ttft_s: Optional[float] = None,
                 prompt_buckets="pow2", session_store=None):
        self.pool = ReplicaPool(step_retry=step_retry)
        # durable sessions: the shared manifest store (a path or a
        # SessionStore) every replica can resolve a returning session id
        # from, plus the gateway's own record of each session's last full
        # sequence and serving replica (the local fast path / pin target)
        if isinstance(session_store, str):
            from ..session_store import SessionStore
            session_store = SessionStore(session_store)
        self.session_store = session_store
        self._session_tokens: Dict[str, np.ndarray] = {}
        self._session_last_replica: Dict[str, str] = {}
        self.router = resolve_policy(policy)
        self.quotas = (quotas if isinstance(quotas, TenantQuotas)
                       else TenantQuotas(quotas))
        self._queue = DispatchQueue(low_share=low_share)
        # admit lock: serializes off-thread submitters (an RPC frontend)
        # against the control loop's dispatch/expire queue harvest.
        # Held only for queue/bookkeeping spans — never across a replica
        # step or a batcher submit (those block on device work; see
        # CC402). Lock order when nested elsewhere is always
        # Gateway._admit -> Batcher._intake, never the reverse.
        from ...utils.locks import TracedRLock
        self._admit = TracedRLock("Gateway._admit")
        self._max_queue_depth = max_queue_depth
        self._default_deadline_s = default_deadline_s
        self.max_request_attempts = max_request_attempts
        self._slo_tpot_s = slo_tpot_s
        self._slo_ttft_s = slo_ttft_s
        self._tpot_ewma: Optional[float] = None
        self._ladder = resolve_ladder(prompt_buckets)
        self._next_gid = 0
        # every live (queued or in-flight) request; terminal ones move to
        # _finished/_failed exactly once
        self._requests: Dict[int, GatewayRequest] = {}
        self._finished: Dict[int, GatewayRequest] = {}
        self._failed: Dict[int, Exception] = {}
        self._sessions: Dict[int, StreamingSession] = {}
        self._tele = _GatewayStats()

    # -- pool lifecycle -------------------------------------------------------
    def add_replica(self, name: str, batcher,
                    weight: float = 1.0) -> Replica:
        return self.pool.add(name, batcher, weight=weight)

    def drain_replica(self, name: str, requeue: bool = False):
        """Stop routing new work to ``name``. By default in-flight work
        finishes on the draining replica (it keeps stepping). With
        ``requeue`` the in-flight requests move back to the gateway
        queue NOW and resume on survivors — token-exact from
        ``prompt ⧺ delivered`` with the same lost/dup accounting guard
        as the death path — so the replica empties immediately
        (scale-down and remediation don't wait out a long decode).
        Post-drain spans carry ``drained=1`` baggage."""
        rep = self.pool.get(name)
        self.pool.drain(name)
        if requeue and rep.alive and rep.load > 0:
            if isinstance(self.router, SessionAffinityPolicy):
                self.router.forget_replica(name)
            self._requeue_from(rep, drained=True)
        # session pins are deliberately PRESERVED across a drain: the
        # replica stays warm, so a later resume can still ride its
        # tiered chain; manifests in the shared store are untouched
        pins = len(getattr(rep.batcher, "_session_pins", {}) or {})
        if pins:
            from ...observability.fleet import spool_event
            spool_event("session", op="drain_preserve", replica=name,
                        sessions=pins)

    def remove_replica(self, name: str, force: bool = False) -> Replica:
        """Remove ``name`` from the pool. ``force`` requeues its
        in-flight requests onto the survivors first (the administrative
        twin of the death path — same ``gateway.requeued`` accounting)."""
        rep = self.pool.get(name)
        if force and rep.load > 0:
            self._requeue_from(rep)
        return self.pool.remove(name, force=force)

    # -- admission ------------------------------------------------------------
    def _feasible(self, max_new: int, budget: float) -> bool:
        tpot = self._slo_tpot_s if self._slo_tpot_s is not None \
            else self._tpot_ewma
        if tpot is None:
            return True             # no estimate yet — admit
        ttft = self._slo_ttft_s if self._slo_ttft_s is not None else tpot
        return ttft + max(0, max_new - 1) * tpot <= budget

    def submit(self, prompt_ids, max_new_tokens: int,
               tenant: str = "default", priority=PRIORITY_HIGH,
               deadline_s: Optional[float] = None,
               session_id: Optional[str] = None) -> int:
        """Admit a request into the gateway queue; returns its gid.

        Raises ``Overloaded`` when the tenant's token bucket can't cover
        ``len(prompt) + max_new_tokens`` or the gateway queue is at
        capacity, ``DeadlineExceeded`` when the deadline cannot be met
        even by the current TPOT estimate, ``ValueError`` when no
        replica in the pool could ever hold the request.
        """
        prompt = np.asarray(prompt_ids, np.int64).reshape(-1)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        pr = _PRIORITIES.get(priority)
        if pr is None:
            raise ValueError(f"unknown priority {priority!r} "
                             f"(high/low or 0/1)")
        reps = self.pool.replicas()
        if reps and len(prompt) + max_new_tokens > max(
                r.batcher.s_max for r in reps):
            raise ValueError(
                f"prompt {len(prompt)} + {max_new_tokens} exceeds every "
                f"replica's slot capacity")
        cost = len(prompt) + max_new_tokens
        if not self.quotas.admit(tenant, cost):
            self._tele.tenant_shed_c.labels(tenant=tenant).inc()
            raise Overloaded(
                f"tenant {tenant!r} quota exhausted "
                f"(cost {cost} tokens)")
        with self._admit:
            if self._max_queue_depth is not None \
                    and len(self._queue) >= self._max_queue_depth:
                self._tele.shed += 1
                self._tele.shed_c.inc()
                raise Overloaded(
                    f"gateway queue at capacity "
                    f"({len(self._queue)}/{self._max_queue_depth})")
            budget = deadline_s if deadline_s is not None \
                else self._default_deadline_s
            if budget is not None and not self._feasible(max_new_tokens,
                                                         budget):
                self._tele.infeasible += 1
                self._tele.infeasible_c.inc()
                raise DeadlineExceeded(
                    f"deadline {budget:.3f}s infeasible for "
                    f"{max_new_tokens} tokens at the current latency "
                    f"estimate")
            now = _time.perf_counter()
            gid = self._next_gid
            self._next_gid += 1
            req = GatewayRequest(
                gid=gid, tenant=tenant, prompt=prompt,
                max_new_tokens=max_new_tokens, priority=pr,
                session_id=session_id,
                bucket=(self._ladder.bucket(len(prompt))
                        if self._ladder is not None else None),
                submit_t=now,
                deadline_t=None if budget is None else now + budget)
            if _trace.enabled():
                # one trace per request, minted HERE: every downstream
                # span (queue/admit/prefill/decode/stream) shares this
                # trace_id, including after a requeue off a dead replica
                req.trace = _trace.new_trace("gateway.request", gid=gid,
                                             tenant=tenant,
                                             rung=req.bucket)
                req.spans["queue"] = req.trace.begin(
                    "queue", priority=req.priority)
            self._requests[gid] = req
            self._queue.push(req)
        self._tele.requests += 1
        self._tele.requests_c.inc()
        self._tele.queue_depth_g.set(len(self._queue))
        return gid

    def stream(self, prompt_ids, max_new_tokens: int,
               max_buffered: int = 64, **kw) -> StreamingSession:
        """submit + open_stream in one call."""
        gid = self.submit(prompt_ids, max_new_tokens, **kw)
        return self.open_stream(gid, max_buffered=max_buffered)

    def open_stream(self, gid: int,
                    max_buffered: int = 64) -> StreamingSession:
        req = self._requests.get(gid)
        if req is None:
            raise KeyError(f"request {gid} is not live "
                           f"(finished, failed, or unknown)")
        if gid in self._sessions:
            return self._sessions[gid]
        sess = StreamingSession(self, req, max_buffered=max_buffered)
        self._sessions[gid] = sess
        return sess

    def _on_session_closed(self, sess: StreamingSession):
        self._sessions.pop(sess.gid, None)

    # -- the control loop -----------------------------------------------------
    def step(self) -> List[int]:
        """One control-plane tick: expire queued deadlines, dispatch,
        step every live replica (under the pool's retry/death policy),
        deliver new tokens, harvest finished requests. Returns the gids
        that finished during THIS call."""
        self._expire_queued()
        self._dispatch()
        for rep in list(self.pool.live()):
            if not rep.batcher._has_work():
                continue
            status, payload = self.pool.step_replica(rep)
            if status == "dead":
                if isinstance(self.router, SessionAffinityPolicy):
                    self.router.forget_replica(rep.name)
                self._requeue_from(rep)
        finished = self._poll()
        self._update_gauges()
        from ...observability.fleet import autospool_tick
        autospool_tick()   # rank-sharded metrics spool; no-op unarmed
        return finished

    def _expire_queued(self):
        now = _time.perf_counter()
        with self._admit:
            expired = [r for r in self._requests.values()
                       if r.replica is None and r.deadline_t is not None
                       and now > r.deadline_t]
            for req in expired:
                self._queue.remove(req)
        for req in expired:
            self._fail(req, DeadlineExceeded(
                f"request {req.gid} expired in the gateway queue"))

    def _throttled(self) -> bool:
        return any(s.throttled for s in self._sessions.values())

    def _dispatch(self):
        if self._throttled():
            # backpressure: a full session buffer pauses INTAKE (a
            # batched decode can't pause one slot); decode continues
            _stream_backpressure()
            return
        while True:
            # queue inspection + pop under the admit lock; the actual
            # assignment (which enters the replica batcher's submit and
            # may do real work) runs with it released
            with self._admit:
                if not len(self._queue):
                    break
                req = self._queue.peek()
                need = (len(req.prompt) + len(req.delivered)
                        + req.remaining)
                cands = [r for r in self.pool.routable()
                         if r.free_slots > 0 and need <= r.batcher.s_max]
                if not cands:
                    break
                rep = self.router.select(req, cands)
                self._queue.pop()
            try:
                self._assign(req, rep)
            except Overloaded:
                # replica-side queue rejected it after our capacity
                # check (a tiny batcher max_queue_depth): keep it ours
                with self._admit:
                    self._queue.push_front(req)
                break

    def _assign(self, req: GatewayRequest, rep: Replica):
        now = _time.perf_counter()
        budget = None if req.deadline_t is None else req.deadline_t - now
        if budget is not None and budget <= 0:
            with self._admit:
                self._queue.remove(req)
            self._fail(req, DeadlineExceeded(
                f"request {req.gid} expired before dispatch"))
            return
        ids = (np.concatenate([req.prompt,
                               np.asarray(req.delivered, np.int64)])
               if req.delivered else req.prompt)
        qs = req.spans.pop("queue", None)
        if qs is not None:
            qs.end(replica=rep.name, attempt=req.attempts + 1)
        if req.trace is not None:
            # baggage merges into every span begun from here on: batcher
            # spans name the replica (and TP shard members) serving them
            # — after a requeue the NEXT assignment overwrites these, so
            # post-failover spans carry the survivor
            req.trace.baggage["replica"] = rep.name
            group = rep.shard_group
            if group is not None:
                req.trace.baggage["tp_group"] = group.name
                req.trace.baggage["tp_members"] = ",".join(group.members)
            else:
                req.trace.baggage.pop("tp_group", None)
                req.trace.baggage.pop("tp_members", None)
        req.rid = rep.batcher.submit(ids, req.remaining,
                                     deadline_s=budget,
                                     trace=req.trace)
        req.replica = rep.name
        req._consumed = 0
        req.attempts += 1
        self.router.on_dispatch(req, rep)
        self._tele.dispatch_c.inc()

    def _requeue_from(self, rep: Replica, drained: bool = False):
        """Move every request assigned to ``rep`` back into the gateway
        queue (head of its lane). Called on replica death, forced
        removal, and requeue-drain (``drained``: the replica is ALIVE —
        deliver its pending decoded tokens first, then withdraw the
        batcher-side request so both engines never decode the same
        request). Requests that already exhausted their attempt budget
        fail typed instead of cycling forever."""
        for req in [r for r in self._requests.values()
                    if r.replica == rep.name]:
            # a request that FINISHED before the death is a completion,
            # not a casualty — harvest it (its final poll may not have
            # run yet). On a live drain, poll unconditionally: tokens a
            # healthy engine already decoded are valid — delivering them
            # now shrinks the survivor's recompute to exactly
            # prompt ⧺ delivered
            breq = rep.batcher.request(req.rid)
            if breq is not None and (breq.finished or drained):
                self._poll_one(req, rep)
                if req.gid not in self._requests:
                    continue
            if drained:
                rep.batcher.abort(req.rid)
            # close the old replica's open batcher spans, then mark the
            # trace so every span begun AFTER this point carries
            # requeued=1 (baggage merges at begin time)
            if breq is not None and breq.spans:
                _trace.end_open_spans(breq.spans, interrupted=1)
            if req.trace is not None:
                req.trace.baggage["requeued"] = 1
                if drained:
                    req.trace.baggage["drained"] = 1
                req.trace.event("requeue", replica=rep.name,
                                drained=int(drained),
                                delivered=len(req.delivered))
            req.replica = None
            req.rid = None
            req._consumed = 0
            if req.attempts >= self.max_request_attempts:
                self._fail(req, Overloaded(
                    f"request {req.gid} exhausted "
                    f"{self.max_request_attempts} dispatch attempts "
                    f"(replicas kept dying under it)"))
                continue
            with self._admit:
                self._queue.push_front(req)
            if req.trace is not None:
                req.spans["queue"] = req.trace.begin("queue",
                                                     priority=req.priority)
            self._tele.requeued += 1
            self._tele.requeued_c.inc()

    # -- token delivery / harvest ---------------------------------------------
    def _poll(self) -> List[int]:
        finished = []
        for req in [r for r in self._requests.values()
                    if r.replica is not None]:
            rep = self.pool.get(req.replica)
            if self._poll_one(req, rep):
                finished.append(req.gid)
        return finished

    def _poll_one(self, req: GatewayRequest, rep: Replica) -> bool:
        """Deliver new tokens for one assignment; harvest if terminal.
        Returns True when the request FINISHED during this poll."""
        breq = rep.batcher.request(req.rid)
        if breq is not None and len(breq.tokens) > req._consumed:
            self._deliver(req, [int(t)
                                for t in breq.tokens[req._consumed:]])
            req._consumed = len(breq.tokens)
        if rep.batcher.failure(req.rid) is not None:
            try:
                rep.batcher.pop_result(req.rid)
            except Exception as exc:  # noqa: BLE001 — typed, re-homed
                self._fail(req, exc)
            return False
        if breq is not None and breq.finished:
            out = rep.batcher.pop_result(req.rid)
            full = np.concatenate(
                [req.prompt, np.asarray(req.delivered, np.int64)])
            if not np.array_equal(out, full):
                # a mismatch here IS a lost/duplicated token — fail loud
                raise RuntimeError(
                    f"gateway token accounting diverged for request "
                    f"{req.gid}: replica returned {len(out)} tokens, "
                    f"gateway delivered {len(full)}")
            self._finish(req)
            return True
        return False

    def _deliver(self, req: GatewayRequest, toks: List[int]):
        now = _time.perf_counter()
        if req.first_token_t is None and toks:
            req.first_token_t = now
            ttft = now - req.submit_t
            self._tele.ttft_h.observe(ttft)
            if req.bucket is not None:
                # rung-labeled twin (the unlabeled series stays — slo.py
                # and the benches consume it by exact name)
                self._tele.ttft_rung_h.labels(
                    rung=str(req.bucket)).observe(ttft)
        req.delivered.extend(toks)
        self._tele.tokens += len(toks)
        self._tele.tokens_c.inc(len(toks))
        sess = self._sessions.get(req.gid)
        if sess is not None:
            sess.push(toks)

    def _finish(self, req: GatewayRequest):
        req.finished = True
        req.finish_t = _time.perf_counter()
        if req.session_id is not None:
            # the session's authoritative context after this turn —
            # what pause_session publishes and a local resume reuses
            self._session_tokens[req.session_id] = np.concatenate(
                [req.prompt, np.asarray(req.delivered, np.int64)])
            if req.replica is not None:
                self._session_last_replica[req.session_id] = req.replica
        if req.spans:
            _trace.end_open_spans(req.spans)
        if req.trace is not None:
            req.trace.finish(tokens=len(req.delivered),
                             attempts=req.attempts)
        del self._requests[req.gid]
        self._finished[req.gid] = req
        self._tele.completions += 1
        self._tele.completions_c.inc()
        n = len(req.delivered)
        if n > 1 and req.first_token_t is not None:
            tpot = (req.finish_t - req.first_token_t) / (n - 1)
            self._tele.tpot_h.observe(tpot)
            self._tpot_ewma = (tpot if self._tpot_ewma is None
                               else 0.8 * self._tpot_ewma + 0.2 * tpot)

    def _fail(self, req: GatewayRequest, exc: Exception):
        req.failure = exc
        if req.spans:
            _trace.end_open_spans(req.spans, error=type(exc).__name__)
        if req.trace is not None:
            req.trace.finish(error=type(exc).__name__)
        self._requests.pop(req.gid, None)
        self._failed[req.gid] = exc
        if isinstance(exc, DeadlineExceeded):
            self._tele.expired += 1
            self._tele.expired_c.inc()
        else:
            self._tele.failures += 1
            self._tele.failures_c.inc()

    def _update_gauges(self):
        self._tele.queue_depth_g.set(len(self._queue))
        self._tele.inflight_g.set(
            sum(1 for r in self._requests.values()
                if r.replica is not None))
        buffered = sum(s.buffered for s in self._sessions.values())
        _stream_buffered_gauge().set(buffered)

    # -- durable sessions -----------------------------------------------------
    def _session_paged_target(self, session_id: str):
        """The replica whose cache should hold the session's chain: the
        one that served its last turn if it's still in the pool and
        alive, else None (resume will route by prefix depth/fallback)."""
        name = self._session_last_replica.get(session_id)
        if name is None and isinstance(self.router, SessionAffinityPolicy):
            name = self.router._sessions.get(session_id)
        if name is None:
            return None
        try:
            rep = self.pool.get(name)
        except KeyError:
            return None
        return rep if rep.alive else None

    def pause_session(self, session_id: str) -> bool:
        """Pause a conversation the gateway served: session-pin its KV
        chain on the replica that holds it (churn may demote the chain
        but can't drop it past the last tier) and publish the crash-safe
        manifest to the shared store, so the session survives that
        replica's death and a fleet rescale. True iff the manifest
        published atomically. Raises ``KeyError`` for a session id the
        gateway never finished a turn for."""
        toks = self._session_tokens.get(session_id)
        if toks is None:
            raise KeyError(f"session {session_id!r}: no finished turn "
                           f"to pause")
        rep = self._session_paged_target(session_id)
        pinned = 0
        for r in self.pool.replicas():
            b = r.batcher
            if not hasattr(b, "pin_session"):
                continue
            if rep is not None and r.name == rep.name:
                pinned = b.pin_session(session_id, toks)
            elif session_id in getattr(b, "_session_pins", {}):
                # a stale pin from an earlier turn on another replica
                b.unpin_session(session_id)
        published = False
        if self.session_store is not None:
            from ..session_store import SessionManifest, model_identity
            src = rep if rep is not None else next(
                (r for r in self.pool.replicas()
                 if hasattr(r.batcher, "block_size")), None)
            bs = src.batcher.block_size if src is not None else 16
            ident = (model_identity(src.batcher.model)
                     if src is not None else "")
            published = self.session_store.publish(SessionManifest(
                session_id=session_id,
                token_ids=[int(t) for t in toks],
                block_size=bs, model=ident))
        from ...observability.fleet import spool_event
        spool_event("session", op="pause", session=session_id,
                    replica=rep.name if rep is not None else "",
                    blocks=pinned, published=int(published))
        return published

    def resume_session(self, session_id: str, new_tokens=None,
                       max_new_tokens: int = 32, tenant: str = "default",
                       priority=PRIORITY_HIGH,
                       deadline_s: Optional[float] = None,
                       fallback_tokens=None) -> int:
        """Resume a paused session on whichever replica the router picks:
        the context comes from the shared manifest (replica-independent —
        this works on a gateway process that never saw the session), or,
        when the manifest is missing/torn/corrupt, from the gateway's
        local record or the caller's ``fallback_tokens`` — a typed
        finding lands in the store and the resume degrades to full
        re-prefill, token-exact either way. The new turn's ``new_tokens``
        are appended to the resolved context; returns the gid."""
        base = None
        source = "manifest"
        if self.session_store is not None:
            m = self.session_store.load(session_id)
            if m is not None:
                base = np.asarray(m.token_ids, np.int64)
        if base is None:
            base = self._session_tokens.get(session_id)
            source = "local"
            if base is None and fallback_tokens is not None:
                base = np.asarray(fallback_tokens, np.int64).reshape(-1)
                source = "caller"
            if base is None:
                raise KeyError(
                    f"session {session_id!r}: no manifest, no local "
                    f"record, no fallback_tokens — cannot reconstruct "
                    f"context")
            self._session_fallback_c().inc()
        if new_tokens is not None and len(np.atleast_1d(new_tokens)):
            prompt = np.concatenate(
                [base, np.asarray(new_tokens, np.int64).reshape(-1)])
        else:
            prompt = base
        gid = self.submit(prompt, max_new_tokens, tenant=tenant,
                          priority=priority, deadline_s=deadline_s,
                          session_id=session_id)
        self._requests[gid].resumed = True
        from ...observability.fleet import spool_event
        spool_event("session", op="resume", session=session_id,
                    source=source, tokens=len(prompt), gid=gid)
        return gid

    def release_session(self, session_id: str,
                        delete_manifest: bool = False):
        """Forget a session fleet-wide: unpin its chain on every replica,
        drop the gateway's local record and sticky routing, and (opt-in)
        delete the manifest."""
        for r in self.pool.replicas():
            if hasattr(r.batcher, "unpin_session"):
                r.batcher.unpin_session(session_id)
        self._session_tokens.pop(session_id, None)
        self._session_last_replica.pop(session_id, None)
        if isinstance(self.router, SessionAffinityPolicy):
            self.router.forget_session(session_id)
        if delete_manifest and self.session_store is not None:
            self.session_store.delete(session_id)
        from ...observability.fleet import spool_event
        spool_event("session", op="release", session=session_id,
                    deleted=int(delete_manifest))

    def _session_fallback_c(self):
        if not hasattr(self, "_session_fb_c"):
            from ...observability.metrics import get_registry
            self._session_fb_c = get_registry().counter(
                "session.resume_fallbacks",
                "resumes served from local/caller context because the "
                "manifest was missing or rejected (full re-prefill)")
        return self._session_fb_c

    # -- results --------------------------------------------------------------
    def _has_work(self) -> bool:
        return bool(self._requests)

    def result(self, gid: int) -> np.ndarray:
        """Full sequence (prompt + generated); raises the request's typed
        failure if it was shed/expired instead of completed."""
        if gid in self._failed:
            raise self._failed[gid]
        req = self._finished[gid]
        return np.concatenate(
            [req.prompt, np.asarray(req.delivered, np.int64)])

    def pop_result(self, gid: int) -> np.ndarray:
        if gid in self._failed:
            raise self._failed.pop(gid)
        out = self.result(gid)
        del self._finished[gid]
        self._sessions.pop(gid, None)
        return out

    def run_until_done(self, max_steps: int = 10000) -> Dict[int, np.ndarray]:
        """Drive the plane until every live request completes; returns
        (and releases) THIS run's finished results. Raises when the step
        budget runs out with work stranded (e.g. the whole pool died) —
        a silent partial dict would read as lost requests."""
        done: List[int] = []
        for _ in range(max_steps):
            done += self.step()
            if not self._has_work():
                break
        else:
            raise RuntimeError(
                f"run_until_done: {len(self._queue)} queued / "
                f"{sum(1 for r in self._requests.values() if r.replica)} "
                f"in-flight requests remain after {max_steps} steps")
        return {gid: self.pop_result(gid) for gid in done}

    # -- monitoring -----------------------------------------------------------
    def stats(self) -> Dict[str, object]:
        t = self._tele
        dt = max(_time.perf_counter() - t.t0, 1e-9)
        return {
            "requests": t.requests,
            "completions": t.completions,
            "requeued": t.requeued,
            "shed": t.shed,
            "infeasible": t.infeasible,
            "deadline_expired": t.expired,
            "failures": t.failures,
            "delivered_tokens": t.tokens,
            "tokens_per_sec": t.tokens / dt,
            "queue_depth": len(self._queue),
            "inflight": sum(1 for r in self._requests.values()
                            if r.replica is not None),
            "replicas": {r.name: {"alive": r.alive,
                                  "draining": r.draining,
                                  "load": r.load,
                                  "health": r.health.state}
                         for r in self.pool.replicas()},
            "elapsed_s": dt,
        }

    def reset_stats(self):
        self._tele.reset()


def _stream_backpressure():
    from .streaming import _stream_metrics
    _stream_metrics()[1].inc()


def _stream_buffered_gauge():
    from .streaming import _stream_metrics
    return _stream_metrics()[0]
