"""Queue-depth + TTFT-driven autoscaling over the gateway pool.

The capacity half of the self-healing loop (the remediation half is
``resilience.remediator``): an ``Autoscaler`` watches the two signals
the gateway already publishes — live queue depth and the cumulative
``gateway.ttft_seconds`` histogram — and adds or drains replicas
through the pool's EXISTING lifecycle. Scale-up builds a fresh engine
from the deployment's ``replica_factory``; scale-down uses
``Gateway.drain_replica(name, requeue=True)`` (in-flight work resumes
on survivors token-exact) and removes the replica once it is empty, so
no request is ever stranded on a scaling decision.

Pressure, not instantaneous readings, drives decisions: a tick counts
toward scale-up when queue depth sits at/above ``queue_high`` OR the
TTFT breach fraction since the last tick (share of completions slower
than ``ttft_slo_s``, read as histogram deltas — no second event pipe)
exceeds ``breach_frac``; toward scale-down when the queue is at/below
``queue_low`` with idle capacity. Only ``hysteresis`` CONSECUTIVE
pressure ticks act, a shared cooldown separates actions, and every
action passes the same ``FlapGuard`` the remediator uses (hand both
the same instance and the two controllers share one action budget —
the autoscaler cannot flap capacity while the remediator is frozen).

``tick()`` is the autonomous gated path. ``scale_up()``/``scale_down()``
are the command surface (the remediator's delegate) — the caller has
already spent flap-guard budget, so they only honor min/max bounds.
"""
from __future__ import annotations

import bisect
import time
from typing import Callable, Dict, List, Optional, Tuple

from ...observability.metrics import Histogram, get_registry
from ...resilience.remediator import FlapGuard
from ...utils.locks import TracedLock

__all__ = ["Autoscaler"]


class Autoscaler:
    """Replica add/drain controller riding the pool lifecycle."""

    def __init__(self, gw, replica_factory: Callable[[str], object],
                 min_replicas: int = 1, max_replicas: int = 4,
                 queue_high: int = 8, queue_low: int = 0,
                 ttft_slo_s: Optional[float] = None,
                 breach_frac: float = 0.5, min_breach_samples: int = 4,
                 hysteresis: int = 3, cooldown_s: float = 30.0,
                 flap_guard: Optional[FlapGuard] = None,
                 clock: Callable[[], float] = time.monotonic):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        self.gw = gw
        self.replica_factory = replica_factory
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_high = int(queue_high)
        self.queue_low = int(queue_low)
        self.ttft_slo_s = ttft_slo_s
        self.breach_frac = float(breach_frac)
        self.min_breach_samples = int(min_breach_samples)
        self.hysteresis = int(hysteresis)
        self.cooldown_s = float(cooldown_s)
        self.flap_guard = flap_guard or FlapGuard(clock=clock)
        self._clock = clock
        self._reg = get_registry()
        # tick-state lock: guards the pressure streaks and cooldown stamp
        # against off-thread observers. Never held across scale_up/
        # scale_down (they call into the gateway pool, which may take
        # Gateway._admit) — the only cross-object lock order is
        # Autoscaler._tick -> Gateway._admit.
        self._tick_lock = TracedLock("Autoscaler._tick")
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_t = -float("inf")
        self._last_ttft: Tuple[int, int] = self._ttft_counts()
        self._next_id = 0
        self._draining: List[str] = []     # replicas we drained, pending removal
        self.events: List[dict] = []
        self._scale_c = self._reg.counter(
            "autoscaler.scale_events", "autoscaler actions taken",
            labelnames=("direction",))
        self._size_g = self._reg.gauge(
            "autoscaler.pool_size", "routable replicas after the last tick")

    # -- TTFT pressure (histogram deltas, the slo.py reading pattern) ---------
    def _ttft_counts(self) -> Tuple[int, int]:
        entry = self._reg.get("gateway.ttft_seconds")
        if entry is None or self.ttft_slo_s is None:
            return 0, 0
        children = (entry.children() if hasattr(entry, "children")
                    else [entry])
        total = good = 0
        for h in children:
            if not isinstance(h, Histogram):
                continue
            counts = h.bucket_counts()
            k = bisect.bisect_right(h.buckets, self.ttft_slo_s + 1e-12)
            total += sum(counts)
            good += sum(counts[:k])
        return total, good

    def _ttft_pressure(self) -> bool:
        cur = self._ttft_counts()
        last, self._last_ttft = self._last_ttft, cur
        d_total = cur[0] - last[0]
        if self.ttft_slo_s is None or d_total < self.min_breach_samples:
            return False
        d_bad = d_total - (cur[1] - last[1])
        return d_bad / d_total >= self.breach_frac

    # -- the autonomous tick --------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One gated control decision; returns ``"scale_up:<name>"`` /
        ``"scale_down:<name>"`` when an action was taken, else None."""
        now = self._clock() if now is None else now
        self._finalize()
        routable = self.gw.pool.routable()
        self._size_g.set(len(routable))
        depth = len(self.gw._queue)
        ttft_hot = self._ttft_pressure()
        with self._tick_lock:
            if depth >= self.queue_high or ttft_hot:
                self._up_streak += 1
                self._down_streak = 0
            elif depth <= self.queue_low and all(
                    r.free_slots > 0 for r in routable):
                self._down_streak += 1
                self._up_streak = 0
            else:
                self._up_streak = 0
                self._down_streak = 0
            if now - self._last_action_t < self.cooldown_s:
                return None
        if self._up_streak >= self.hysteresis \
                and len(routable) < self.max_replicas:
            ok, why = self.flap_guard.check(now)
            if not ok:
                self._journal("scale_up", "", why, now,
                              depth=depth, ttft_hot=int(ttft_hot))
                with self._tick_lock:
                    self._up_streak = 0
                return None
            name = self.scale_up(
                reason="queue" if depth >= self.queue_high else "ttft",
                now=now)
            if name is not None:
                self.flap_guard.record(now)
                with self._tick_lock:
                    self._up_streak = 0
                return f"scale_up:{name}"
        if self._down_streak >= self.hysteresis \
                and len(routable) > self.min_replicas:
            ok, why = self.flap_guard.check(now)
            if not ok:
                self._journal("scale_down", "", why, now, depth=depth)
                with self._tick_lock:
                    self._down_streak = 0
                return None
            name = self.scale_down(reason="idle", now=now)
            if name is not None:
                self.flap_guard.record(now)
                with self._tick_lock:
                    self._down_streak = 0
                return f"scale_down:{name}"
        return None

    # -- the command surface (min/max-bounded, caller owns the guard) ---------
    def scale_up(self, reason: str = "",
                 now: Optional[float] = None) -> Optional[str]:
        now = self._clock() if now is None else now
        if len(self.gw.pool.routable()) >= self.max_replicas:
            self._journal("scale_up", "", "at_max", now)
            return None
        name = f"auto{self._next_id}"
        self._next_id += 1
        self.gw.add_replica(name, self.replica_factory(name))
        self._last_action_t = now
        self._scale_c.labels(direction="up").inc()
        self._journal("scale_up", name, "executed", now, cause=reason)
        return name

    def scale_down(self, reason: str = "",
                   now: Optional[float] = None) -> Optional[str]:
        now = self._clock() if now is None else now
        cands = self.gw.pool.routable()
        if len(cands) <= self.min_replicas:
            self._journal("scale_down", "", "at_min", now)
            return None
        # prefer retiring our own additions, then the least-loaded
        auto = [r for r in cands if r.name.startswith("auto")]
        victim = min(auto or cands, key=lambda r: (r.load, r.name))
        self.gw.drain_replica(victim.name, requeue=True)
        self._draining.append(victim.name)
        self._finalize()
        self._last_action_t = now
        self._scale_c.labels(direction="down").inc()
        self._journal("scale_down", victim.name, "executed", now,
                      cause=reason)
        return victim.name

    def _finalize(self):
        """Remove replicas we drained once their last request left."""
        for name in list(self._draining):
            if name not in self.gw.pool:
                self._draining.remove(name)
                continue
            rep = self.gw.pool.get(name)
            if rep.load == 0 or not rep.alive:
                self.gw.remove_replica(name, force=not rep.alive)
                self._draining.remove(name)

    def _journal(self, action: str, target: str, decision: str,
                 now: float, **detail):
        ev = {"action": action, "target": target, "decision": decision,
              "at": now, **detail}
        self.events.append(ev)
        from ...observability.fleet import spool_event
        from ...observability.flight import flight_record
        spool_event("remediation", actor="autoscaler", action=action,
                    target=target, decision=decision, **detail)
        flight_record("remediation", actor="autoscaler", action=action,
                      target=target, decision=decision)
