"""Incremental token delivery with backpressure.

``StreamingSession`` is the caller-facing handle for one gateway
request: iterate it to receive tokens as the replicas produce them
(TTFT-shaped delivery) instead of waiting for ``run_until_done``. The
gateway pushes tokens into the session's buffer after every step; a
consumer pulling an empty buffer DRIVES ``gateway.step()`` — the whole
control plane is single-threaded and consumer-paced, so no real
concurrency is needed for the simulation harness or the tests.

Backpressure: a batched decode step cannot pause one slot, so per-slot
flow control is impossible — the honest lever is INTAKE. While any open
session's buffer sits at/above ``max_buffered``, the gateway counts
``gateway.stream.backpressure`` and pauses dispatching NEW queued work
(decode of in-flight requests continues; buffered tokens are never
dropped). Consume or ``close()`` sessions you stop reading, or queued
requests wait behind the throttle.

Requeue transparency: a replica dying mid-stream is invisible here —
the gateway resumes the request on a survivor and the continuation
tokens arrive through the same buffer, exactly once each.
"""
from __future__ import annotations

from collections import deque
from typing import Iterator, List, Optional

__all__ = ["StreamingSession"]


def _stream_metrics():
    from ...observability.metrics import get_registry
    reg = get_registry()
    return (reg.gauge("gateway.stream.buffered",
                      "tokens buffered across open streaming sessions"),
            reg.counter("gateway.stream.backpressure",
                        "steps where a full session buffer paused "
                        "gateway intake"))


class StreamingSession:
    """Iterator over one request's generated tokens."""

    def __init__(self, gateway, req, max_buffered: int = 64):
        if max_buffered < 1:
            raise ValueError("max_buffered must be >= 1")
        self._gw = gateway
        self._req = req
        self.max_buffered = max_buffered
        self._buf: deque = deque()
        self.closed = False

    # -- gateway side ---------------------------------------------------------
    def push(self, tokens: List[int]):
        if self.closed:
            return
        if tokens and self._req.trace is not None \
                and "stream" not in self._req.spans:
            # delivery span: first buffered token -> finish/close
            self._req.spans["stream"] = self._req.trace.begin("stream")
        self._buf.extend(tokens)

    @property
    def buffered(self) -> int:
        return len(self._buf)

    @property
    def throttled(self) -> bool:
        """True while this session's backlog should pause gateway intake."""
        return not self.closed and len(self._buf) >= self.max_buffered

    # -- consumer side --------------------------------------------------------
    @property
    def gid(self) -> int:
        return self._req.gid

    @property
    def done(self) -> bool:
        return self._req.finished or self._req.failure is not None

    def close(self):
        """Detach: stop buffering (already-buffered tokens stay readable)
        and stop counting toward the intake throttle. The request itself
        keeps running; its full result stays available via
        ``gateway.result``."""
        self.closed = True
        sp = self._req.spans.pop("stream", None)
        if sp is not None:
            sp.end(delivered=len(self._req.delivered))
        self._gw._on_session_closed(self)

    def __iter__(self) -> Iterator[int]:
        return self

    def __next__(self) -> int:
        while True:
            if self._buf:
                return self._buf.popleft()
            if self._req.failure is not None:
                raise self._req.failure
            if self._req.finished or self.closed:
                raise StopIteration
            # consumer-paced production: an empty buffer drives the
            # control plane one step
            self._gw.step()

    def read_available(self) -> List[int]:
        """Drain whatever is buffered right now without stepping."""
        out = list(self._buf)
        self._buf.clear()
        return out
