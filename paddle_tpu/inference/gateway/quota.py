"""Per-tenant admission quotas: token buckets.

The gateway charges a request's worst-case token footprint
(``len(prompt) + max_new_tokens``) against its tenant's bucket at
submit time, so one tenant flooding the queue cannot starve the pool —
the classic serving-front-door rate limiter (DistServe/Orca deployments
put exactly this in front of the iteration-level scheduler). Tenants
without a configured bucket are unlimited.

Buckets refill continuously at ``rate`` tokens/second up to ``burst``.
The clock is injectable so tests replay deterministically.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

__all__ = ["TokenBucket", "TenantQuotas"]


class TokenBucket:
    """Continuous-refill token bucket (rate tokens/s, burst capacity)."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        if rate < 0:
            raise ValueError(f"rate must be >= 0, got {rate}")
        if burst <= 0:
            raise ValueError(f"burst must be positive, got {burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._level = float(burst)          # start full
        self._last = clock()

    def _refill(self):
        now = self._clock()
        dt = max(0.0, now - self._last)
        self._last = now
        self._level = min(self.burst, self._level + dt * self.rate)

    @property
    def level(self) -> float:
        self._refill()
        return self._level

    def try_take(self, n: float) -> bool:
        """Charge ``n`` tokens; False (nothing charged) when the bucket
        can't cover it."""
        self._refill()
        if n > self._level:
            return False
        self._level -= n
        return True


class TenantQuotas:
    """tenant -> TokenBucket map with an unlimited default.

    ``admit(tenant, cost)`` returns whether the charge fit; the caller
    (the gateway's submit path) raises the typed ``Overloaded`` on a
    False so quota rejections share the batchers' exception family.
    """

    def __init__(self, buckets: Optional[Dict[str, TokenBucket]] = None):
        self._buckets: Dict[str, TokenBucket] = dict(buckets or {})

    def set_quota(self, tenant: str, bucket: TokenBucket):
        self._buckets[tenant] = bucket

    def bucket(self, tenant: str) -> Optional[TokenBucket]:
        return self._buckets.get(tenant)

    def admit(self, tenant: str, cost: float) -> bool:
        b = self._buckets.get(tenant)
        if b is None:
            return True
        ok = b.try_take(cost)
        _quota_level_g().labels(tenant=tenant).set(b._level)
        return ok


def _quota_level_g():
    from ...observability.metrics import get_registry
    return get_registry().gauge(
        "gateway.quota.level",
        "tenant token-bucket level after the latest admit decision",
        labelnames=("tenant",))
