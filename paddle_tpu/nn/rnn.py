"""Recurrent layers.

Reference: python/paddle/nn/layer/rnn.py — SimpleRNNCell/LSTMCell/GRUCell,
the RNN/BiRNN sequence wrappers, and the multi-layer SimpleRNN/LSTM/GRU
(cudnn-backed kernels in phi/kernels/gpu/rnn_kernel.cu).

TPU-native: a cell step is a couple of MXU matmuls + VPU gates; the time
loop is ONE ``lax.scan`` inside a single dispatched op, so the whole
unrolled sequence (and its backward) compiles into one XLA while-loop —
the cudnn-fused-RNN role. Gate conventions follow the reference:
LSTM gates ordered (i, f, g, o); GRU ordered (u, r, c) with
``h = u * h_prev + (1 - u) * c``.

Sequence lengths: padded steps beyond each sample's length carry the last
valid state forward and zero the output (reference mask semantics).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.registry import dispatch
from . import initializer as I
from .layer import Layer


def _uniform_attr(hidden_size):
    k = 1.0 / math.sqrt(hidden_size)
    return I.Uniform(-k, k)


def _affine(x, w, b):
    """x @ w.T (+ b when the bias exists — bias attrs may be False)."""
    out = x @ w.T
    return out if b is None else out + b


class RNNCellBase(Layer):
    """rnn.py RNNCellBase analog."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ..core.tensor import Tensor
        batch = batch_ref.shape[batch_dim_idx]
        n = self.hidden_size
        mk = lambda: Tensor(jnp.full((batch, n), init_value,  # noqa: E731
                                     dtype=jnp.float32))
        if getattr(self, "state_shape", None) and len(self.state_shape) == 2:
            return (mk(), mk())
        return mk()


class SimpleRNNCell(RNNCellBase):
    """h' = act(x W_ih^T + b_ih + h W_hh^T + b_hh)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        u = _uniform_attr(hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _step(self, x, h, wih, whh, bih, bhh):
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        return act(_affine(x, wih, bih) + _affine(h, whh, bhh))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _impl(x, h, wih, whh, bih, bhh):
            h2 = self._step(x, h, wih, whh, bih, bhh)
            return h2, h2

        out, h = dispatch(_impl, (inputs, states, self.weight_ih,
                                  self.weight_hh, self.bias_ih,
                                  self.bias_hh), {}, op_name="rnn_cell")
        return out, h


class LSTMCell(RNNCellBase):
    """Gates (i, f, g, o); returns (h, (h, c))."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        u = _uniform_attr(hidden_size)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=u)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def _step(self, x, h, c, wih, whh, bih, bhh):
        gates = _affine(x, wih, bih) + _affine(h, whh, bhh)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        return h2, c2

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h0, c0 = states

        def _impl(x, h, c, wih, whh, bih, bhh):
            h2, c2 = self._step(x, h, c, wih, whh, bih, bhh)
            return h2, h2, c2

        out, h, c = dispatch(_impl, (inputs, h0, c0, self.weight_ih,
                                     self.weight_hh, self.bias_ih,
                                     self.bias_hh), {}, op_name="lstm_cell")
        return out, (h, c)


class GRUCell(RNNCellBase):
    """Gates (u, r, c): h' = u * h + (1 - u) * c~ (reference convention)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        u = _uniform_attr(hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=u)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=u)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _step(self, x, h, wih, whh, bih, bhh):
        xu, xr, xc = jnp.split(_affine(x, wih, bih), 3, axis=-1)
        hu, hr, hc = jnp.split(_affine(h, whh, bhh), 3, axis=-1)
        u = jax.nn.sigmoid(xu + hu)
        r = jax.nn.sigmoid(xr + hr)
        c = jnp.tanh(xc + r * hc)
        return u * h + (1.0 - u) * c

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _impl(x, h, wih, whh, bih, bhh):
            h2 = self._step(x, h, wih, whh, bih, bhh)
            return h2, h2

        out, h = dispatch(_impl, (inputs, states, self.weight_ih,
                                  self.weight_hh, self.bias_ih,
                                  self.bias_hh), {}, op_name="gru_cell")
        return out, h


def _scan_cell(cell, x_arr, init_states, weights, seq_lens, is_reverse):
    """One lax.scan over time for any cell (pure; runs inside dispatch).

    x_arr: [B, T, I]; init_states: tuple of [B, H]; weights: flat tuple.
    Returns (outputs [B, T, H], final_states tuple).
    """
    T = x_arr.shape[1]
    xs = jnp.moveaxis(x_arr, 1, 0)                    # [T, B, I]
    if is_reverse:
        xs = xs[::-1]

    def step(states, inp):
        x_t, t_idx = inp
        if len(init_states) == 2:
            h2, c2 = cell._step(x_t, states[0], states[1], *weights)
            new = (h2, c2)
        else:
            h2 = cell._step(x_t, states[0], *weights)
            new = (h2,)
        if seq_lens is not None:
            # time index in ORIGINAL order for this step
            real_t = (T - 1 - t_idx) if is_reverse else t_idx
            valid = (real_t < seq_lens)[:, None]
            new = tuple(jnp.where(valid, n, s)
                        for n, s in zip(new, states))
            out_t = jnp.where(valid, new[0], 0.0)
        else:
            out_t = new[0]
        return new, out_t

    final, outs = jax.lax.scan(step, tuple(init_states),
                               (xs, jnp.arange(T)))
    if is_reverse:
        outs = outs[::-1]
    return jnp.moveaxis(outs, 0, 1), final


class RNN(Layer):
    """rnn.py RNN analog: wraps a cell over the time axis."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            ref = inputs.transpose([1, 0, 2]) if self.time_major else inputs
            initial_states = self.cell.get_initial_states(ref)
        states = (initial_states if isinstance(initial_states, (tuple, list))
                  else (initial_states,))
        cell = self.cell
        weights = (cell.weight_ih, cell.weight_hh, cell.bias_ih,
                   cell.bias_hh)
        time_major = self.time_major
        is_reverse = self.is_reverse
        n_states = len(states)

        def _impl(x, *rest):
            st = rest[:n_states]
            ws = rest[n_states:n_states + 4]
            lens = rest[n_states + 4] if sequence_length is not None else None
            if time_major:
                x = jnp.moveaxis(x, 0, 1)
            outs, final = _scan_cell(cell, x, st, ws, lens, is_reverse)
            if time_major:
                outs = jnp.moveaxis(outs, 1, 0)
            return (outs,) + final

        args = (inputs,) + tuple(states) + weights
        if sequence_length is not None:
            args = args + (sequence_length,)
        res = dispatch(_impl, args, {}, op_name="rnn_scan")
        outs = res[0]
        final = tuple(res[1:])
        return outs, (final if n_states == 2 else final[0])


class BiRNN(Layer):
    """rnn.py BiRNN analog: concatenated fw/bw outputs."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            st_fw = st_bw = None
        else:
            st_fw, st_bw = initial_states
        out_fw, fin_fw = self.rnn_fw(inputs, st_fw, sequence_length)
        out_bw, fin_bw = self.rnn_bw(inputs, st_bw, sequence_length)
        from ..ops.manipulation import concat
        return concat([out_fw, out_bw], axis=-1), (fin_fw, fin_bw)


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) stack of scan-RNNs."""

    CELL = None

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation=None, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if bidirect else 1
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"unsupported direction {direction}")

        kwargs = dict(weight_ih_attr=weight_ih_attr,
                      weight_hh_attr=weight_hh_attr,
                      bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
        if activation is not None:
            kwargs["activation"] = activation

        self._layers_list = []
        for i in range(num_layers):
            in_sz = input_size if i == 0 else hidden_size * \
                self.num_directions
            if bidirect:
                wrap = BiRNN(self.CELL(in_sz, hidden_size, **kwargs),
                             self.CELL(in_sz, hidden_size, **kwargs),
                             time_major=time_major)
            else:
                wrap = RNN(self.CELL(in_sz, hidden_size, **kwargs),
                           time_major=time_major)
            self.add_sublayer(f"{i}", wrap)
            self._layers_list.append(wrap)

    def _slice_states(self, initial_states, layer_idx):
        """Paddle layout: h0 (and c0 for LSTM) are stacked
        [num_layers * num_directions, B, H]; slice this layer's share."""
        if initial_states is None:
            return None
        is_lstm = isinstance(initial_states, (tuple, list))
        D = self.num_directions
        lo = layer_idx * D

        def pick(t, i):
            return t[lo + i]

        if D == 1:
            if is_lstm:
                h0, c0 = initial_states
                return (pick(h0, 0), pick(c0, 0))
            return pick(initial_states, 0)
        if is_lstm:
            h0, c0 = initial_states
            return ((pick(h0, 0), pick(c0, 0)), (pick(h0, 1), pick(c0, 1)))
        return (pick(initial_states, 0), pick(initial_states, 1))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..nn import functional as F
        from ..ops.manipulation import stack
        out = inputs
        h_parts = []
        c_parts = []
        for i, layer in enumerate(self._layers_list):
            out, fin = layer(out, self._slice_states(initial_states, i),
                             sequence_length)
            # normalize this layer's finals to lists of per-direction states
            dirs = fin if self.num_directions == 2 else (fin,)
            for d in dirs:
                if isinstance(d, (tuple, list)):  # LSTM (h, c)
                    h_parts.append(d[0])
                    c_parts.append(d[1])
                else:
                    h_parts.append(d)
            if self.dropout and i < self.num_layers - 1 and self.training:
                out = F.dropout(out, p=self.dropout, training=True)
        h_n = stack(h_parts, axis=0)  # [L * D, B, H] (reference layout)
        if c_parts:
            return out, (h_n, stack(c_parts, axis=0))
        return out, h_n


class SimpleRNN(_RNNBase):
    """nn.SimpleRNN analog."""
    CELL = SimpleRNNCell


class LSTM(_RNNBase):
    """nn.LSTM analog."""
    CELL = LSTMCell


class GRU(_RNNBase):
    """nn.GRU analog."""
    CELL = GRUCell


__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]
