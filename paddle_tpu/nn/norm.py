"""Normalization layers (python/paddle/nn/layer/norm.py analog)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import functional as F
from . import initializer as I
from .layer import Layer


class LayerNorm(Layer):
    """nn.LayerNorm (python/paddle/nn/layer/norm.py:LayerNorm)."""

    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            self.normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    """RMSNorm for llama-family models (ref: incubate fused_rms_norm wrappers)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self.momentum, epsilon=self.epsilon,
                            data_format=self.data_format,
                            use_global_stats=self.use_global_stats)

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """nn.SyncBatchNorm — under SPMD, batch stats are computed over the global
    batch automatically when the batch axis is sharded (GSPMD inserts the
    cross-replica reductions), so this is the same op with a doc contract
    (reference: python/paddle/nn/layer/norm.py:SyncBatchNorm over NCCL)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            new = cls(layer.num_features, layer.momentum, layer.epsilon,
                      data_format=layer.data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new.register_buffer("_mean", layer._mean)
            new.register_buffer("_variance", layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias,
                            self.epsilon, self.data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_features = num_features
        self.epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW"):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k)


class SpectralNorm(Layer):
    """Spectral normalization (python/paddle/nn/layer/norm.py:SpectralNorm;
    phi spectral_norm kernel): power iteration estimates sigma_max of the
    weight viewed as a [dim_axis, -1] matrix; forward returns weight/sigma.
    The u/v estimates persist as non-trainable state (reference behavior)."""

    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        import numpy as _np
        self.axis = axis
        self.power_iters = power_iters
        self.epsilon = epsilon
        h = weight_shape[axis]
        w = int(_np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..ops.registry import dispatch
        axis, eps, iters = self.axis, self.epsilon, self.power_iters

        def _impl(w, u, v):
            mat = jnp.moveaxis(w, axis, 0).reshape(w.shape[axis], -1)
            for _ in range(iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma, u, v

        out, u_new, v_new = dispatch(
            _impl, (weight, self.weight_u, self.weight_v), {},
            op_name="spectral_norm")
        self.weight_u._set_data(u_new._data if isinstance(u_new, Tensor)
                                else u_new)
        self.weight_v._set_data(v_new._data if isinstance(v_new, Tensor)
                                else v_new)
        return out
