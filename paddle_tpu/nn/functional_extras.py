"""nn.functional long tail: 3-D pooling, transposed convs, the loss zoo,
CTC/RNNT, and spatial-transformer ops.

Reference: python/paddle/nn/functional/{pooling,conv,loss,vision,common}.py.
Each entry keeps the paddle signature; kernels are jnp/lax compositions
(reduce_window for pools, conv_general_dilated for convs, log-space scans
for CTC/RNNT — the reference's warp-ctc/cudnn kernels become XLA loops that
fuse on TPU).
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops.registry import defop
from .functional import (_avg_pool, _conv_padding, _max_pool, _max_pool_mask,
                         _pool_dims, _tuple)
from . import functional as F


# ---------------------------------------------------------------------------
# pooling: 3-D + adaptive + unpool + fractional
# ---------------------------------------------------------------------------

def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    window, strides, pads = _pool_dims(data_format, kernel_size, stride,
                                       padding, 3, tuple(x.shape), ceil_mode)
    out = _max_pool(x, window, strides, pads)
    if return_mask:
        return out, Tensor(_max_pool_mask(x._data, window, strides, pads))
    return out


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW"):
    window, strides, pads = _pool_dims(data_format, kernel_size, stride,
                                       padding, 3, tuple(x.shape), ceil_mode)
    return _avg_pool(x, window, strides, pads, exclusive, divisor_override)


def _adaptive_windows(in_size, out_size):
    """Per-output start/end following paddle's floor/ceil rule."""
    starts = [(i * in_size) // out_size for i in range(out_size)]
    ends = [-(-((i + 1) * in_size) // out_size) for i in range(out_size)]
    return starts, ends


def _adaptive_pool_nd(x, output_size, nd, reduce_fn, data_format):
    """Generic adaptive pool over the trailing nd spatial dims (NC-leading)."""
    chan_last = data_format in ("NHWC", "NLC", "NDHWC")
    if chan_last:
        x = jnp.moveaxis(x, -1, 1)
    out_sizes = _tuple(output_size, nd)
    spatial = x.shape[2:]
    out_sizes = tuple(s if o is None else o
                      for o, s in zip(out_sizes, spatial))
    # slice-and-reduce per output cell along each axis in turn
    for ax in range(nd):
        in_size = x.shape[2 + ax]
        starts, ends = _adaptive_windows(in_size, out_sizes[ax])
        pieces = [reduce_fn(jax.lax.slice_in_dim(x, s, e, axis=2 + ax),
                            axis=2 + ax, keepdims=True)
                  for s, e in zip(starts, ends)]
        x = jnp.concatenate(pieces, axis=2 + ax)
    if chan_last:
        x = jnp.moveaxis(x, 1, -1)
    return x


@defop()
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return _adaptive_pool_nd(x, output_size, 3, jnp.mean, data_format)


@defop()
def _adaptive_max_nd(x, output_size, nd, data_format):
    return _adaptive_pool_nd(x, output_size, nd, jnp.max, data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False):
    out = _adaptive_max_nd(x, output_size, 1, "NCL")
    if return_mask:
        return out, _adaptive_max_mask(x, out, 1)
    return out


def adaptive_max_pool3d(x, output_size, return_mask=False):
    out = _adaptive_max_nd(x, output_size, 3, "NCDHW")
    if return_mask:
        return out, _adaptive_max_mask(x, out, 3)
    return out


def _adaptive_max_mask(x, out, nd):
    """Indices of the max per adaptive cell (flattened spatial)."""
    spatial = x.shape[2:]
    flat = np.prod(spatial)
    xr = x._data.reshape(x.shape[0], x.shape[1], -1)
    # brute force: for each output cell value, first matching position
    o = out._data.reshape(out.shape[0], out.shape[1], -1)
    eq = xr[:, :, None, :] == o[:, :, :, None]
    idx = jnp.argmax(eq, axis=-1)
    return Tensor(idx.reshape(out.shape).astype(jnp.int32))


def _unpool_nd(x, indices, kernel_size, stride, padding, output_size, nd,
               data_format):
    """Scatter pooled values back to their argmax positions."""
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    ia = indices._data if isinstance(indices, Tensor) else jnp.asarray(indices)
    n, c = xa.shape[:2]
    if output_size is None:
        kernel = _tuple(kernel_size, nd)
        stridet = _tuple(stride if stride is not None else kernel_size, nd)
        pad = _tuple(padding, nd)
        in_sp = xa.shape[2:]
        output_size = tuple(
            (s - 1) * st + k - 2 * p
            for s, st, k, p in zip(in_sp, stridet, kernel, pad))
    else:
        output_size = tuple(output_size[-nd:])
    flat_out = int(np.prod(output_size))
    zeros = jnp.zeros((n, c, flat_out), xa.dtype)
    scat = zeros.reshape(n * c, flat_out)
    vals = xa.reshape(n * c, -1)
    idx = ia.reshape(n * c, -1).astype(jnp.int32)
    rows = jnp.arange(n * c)[:, None]
    scat = scat.at[rows, idx].set(vals)
    return Tensor(scat.reshape((n, c) + output_size))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _unpool_nd(x, indices, kernel_size, stride, padding, output_size,
                      1, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _unpool_nd(x, indices, kernel_size, stride, padding, output_size,
                      2, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _unpool_nd(x, indices, kernel_size, stride, padding, output_size,
                      3, data_format)


def _fractional_pool(x, output_size, kernel_size, random_u, nd):
    """Fractional max pool (Graham 2014): pseudo-random pooling regions from
    one uniform sample u (paddle's random_u), deterministic under jit."""
    out_sizes = _tuple(output_size, nd)
    if random_u is None:
        from .functional import random_mod
        u = float(jax.random.uniform(random_mod.next_key(), ()))
    else:
        u = float(random_u)
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    for ax in range(nd):
        in_size = xa.shape[2 + ax]
        out_size = out_sizes[ax]
        alpha = in_size / out_size
        # row starts: ceil(alpha*(i+u)) - ceil(alpha*u), clipped (paper eq.)
        base = np.ceil(alpha * (np.arange(out_size) + u)) - np.ceil(alpha * u)
        starts = np.clip(base.astype(int), 0, in_size - 1)
        ends = np.append(starts[1:], in_size)
        pieces = [jnp.max(jax.lax.slice_in_dim(xa, int(s), int(builtins.max(e, s + 1)),
                                               axis=2 + ax),
                          axis=2 + ax, keepdims=True)
                  for s, e in zip(starts, ends)]
        xa = jnp.concatenate(pieces, axis=2 + ax)
    return Tensor(xa)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    out = _fractional_pool(x, output_size, kernel_size, random_u, 2)
    if return_mask:
        return out, _adaptive_max_mask(x, out, 2)
    return out


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    out = _fractional_pool(x, output_size, kernel_size, random_u, 3)
    if return_mask:
        return out, _adaptive_max_mask(x, out, 3)
    return out


# ---------------------------------------------------------------------------
# transposed convs (1d / 3d) — generalize the 2d path
# ---------------------------------------------------------------------------

@defop()
def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, nd, data_format):
    stride = _tuple(stride, nd)
    dilation = _tuple(dilation, nd)
    padn = _conv_padding(padding, nd)
    spatial = tuple(range(2, 2 + nd))
    if isinstance(padn, str):
        padcfg = padn
    else:
        opad = _tuple(output_padding, nd)
        ks = [(weight.shape[2 + i] - 1) * dilation[i] + 1 for i in range(nd)]
        padcfg = [(k - 1 - pl, k - 1 - ph + op)
                  for k, (pl, ph), op in zip(ks, padn, opad)]
    w = jnp.flip(weight, axis=spatial)
    if groups > 1:
        ic, ocg = w.shape[0], w.shape[1]
        w = w.reshape(groups, ic // groups, ocg, *w.shape[2:])
        w = jnp.swapaxes(w, 1, 2).reshape(groups * ocg, ic // groups,
                                          *w.shape[3:])
    else:
        w = jnp.swapaxes(w, 0, 1)
    spatial_chars = {1: "W", 2: "HW", 3: "DHW"}[nd]
    io_spec = "OI" + spatial_chars
    fmt = "NC" + spatial_chars
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, (fmt, io_spec, fmt))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(1,) * nd, padding=padcfg,
        lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)
    if bias is not None:
        shape = [1, -1] + [1] * nd
        out = out + bias.reshape(shape)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL"):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, 1,
                              data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW"):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, 3,
                              data_format)


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

@defop()
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


def _act_inplace(fn, name):
    def op(x, *args, **kwargs):
        if not x.stop_gradient and x.is_leaf:
            raise RuntimeError(
                f"{name}: in-place on a leaf requiring grad is not allowed")
        out = fn(x, *args, **kwargs)
        x._set_data(out._data if isinstance(out, Tensor) else out)
        return x
    op.__name__ = name
    return op


# ---------------------------------------------------------------------------
# padding / shuffles
# ---------------------------------------------------------------------------

def zeropad2d(x, padding, data_format="NCHW", name=None):
    pl, pr, pt, pb = _tuple(padding, 4)
    if data_format == "NCHW":
        cfg = [(0, 0), (0, 0), (pt, pb), (pl, pr)]
    else:
        cfg = [(0, 0), (pt, pb), (pl, pr), (0, 0)]
    return Tensor(jnp.pad(x._data if isinstance(x, Tensor) else x, cfg))


@defop()
def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // r, r, w // r, r)
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4)).reshape(n, c * r * r,
                                                     h // r, w // r)
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 2, 3, 1))
    return x


@defop()
def channel_shuffle(x, groups, data_format="NCHW"):
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    n, c, h, w = x.shape
    x = x.reshape(n, groups, c // groups, h, w)
    x = jnp.swapaxes(x, 1, 2).reshape(n, c, h, w)
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 2, 3, 1))
    return x


# ---------------------------------------------------------------------------
# sequence / misc
# ---------------------------------------------------------------------------

@defop(differentiable=False)
def sequence_mask(x, maxlen=None, dtype="int64"):
    from ..core import dtype as dtype_mod
    if maxlen is None:
        maxlen = int(jnp.max(x))
    pos = jnp.arange(maxlen)
    mask = pos[None, :] < x[..., None]
    return mask.astype(dtype_mod.to_jax_dtype(dtype))


@defop(differentiable=False)
def gather_tree(ids, parents):
    """Beam-search backtrace (paddle.nn.functional.gather_tree):
    ids/parents [T, B, beam] -> full sequences by walking parents from the
    last step backwards."""
    T = ids.shape[0]

    def step(carry, xs):
        beam_idx = carry                     # [B, beam]
        step_ids, step_parents = xs
        out = jnp.take_along_axis(step_ids, beam_idx, axis=1)
        beam_idx = jnp.take_along_axis(step_parents, beam_idx, axis=1)
        return beam_idx, out

    # carry dtype must match the body's output (take_along_axis of parents)
    # or lax.scan rejects the carry under x64 (harness-found)
    init = jnp.tile(jnp.arange(ids.shape[2], dtype=parents.dtype)[None, :],
                    (ids.shape[1], 1))
    _, outs = jax.lax.scan(step, init, (ids[::-1], parents[::-1]))
    return outs[::-1]


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers + remap labels (margin-softmax
    training; ref class_center_sample). Positive classes always kept."""
    from .functional import random_mod
    lab = np.asarray(label._data if isinstance(label, Tensor) else label)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos)
        key = random_mod.next_key()
        perm = np.asarray(jax.random.permutation(key, rest.shape[0]))
        extra = rest[perm[:num_samples - len(pos)]]
        sampled = np.concatenate([pos, np.sort(extra)])
    remap = np.full(num_classes, -1, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor(jnp.asarray(remap[lab])),
            Tensor(jnp.asarray(sampled)))


_CSR_MASK_CACHE: dict = {}  # pattern digest -> (elem_mask, block_mask|None)


def _csr_masks(offs, cols, seq, block):
    """CSR pattern -> (dense [b,h,s,s] bool mask, tile-aligned block mask
    or None). The pattern is static across decode steps, so the O(seq^2)
    host expansion and the alignment probe are cached by content digest."""
    import hashlib
    key = (hashlib.sha1(offs.tobytes()).hexdigest(),
           hashlib.sha1(cols.tobytes()).hexdigest(), seq, block)
    hit = _CSR_MASK_CACHE.get(key)
    if hit is not None:
        return hit
    b, h = offs.shape[0], offs.shape[1]
    mask = np.zeros((b, h, seq, seq), bool)
    for bi in range(b):
        for hi in range(h):
            off = offs[bi, hi]
            col = cols[bi, hi]
            for r in range(seq):
                mask[bi, hi, r, col[off[r]:off[r + 1]]] = True
    block_mask = None
    if seq % block == 0:
        nb = seq // block
        blocks = mask.reshape(b, h, nb, block, nb, block)
        any_ = blocks.any(axis=(3, 5))
        all_ = blocks.all(axis=(3, 5))
        if np.array_equal(any_, all_):  # every active tile fully dense
            first = any_[0, 0]
            if (any_ == first[None, None]).all():  # uniform across b/h
                block_mask = first
    if len(_CSR_MASK_CACHE) >= 8:
        _CSR_MASK_CACHE.pop(next(iter(_CSR_MASK_CACHE)))
    _CSR_MASK_CACHE[key] = (mask, block_mask)
    return mask, block_mask


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention (ref sparse_attention op, GPU-only,
    phi/kernels/gpu/sparse_attention_kernel.cu). When the CSR pattern is
    TILE-aligned, the Pallas block-sparse kernel computes only the active
    tiles on TPU (ops/pallas/block_sparse_attention.py); otherwise the
    pattern is applied densely as a mask (XLA fuses). The pattern
    expansion is cached by content digest (static across decode steps)."""
    q = query._data if isinstance(query, Tensor) else query
    k = key._data if isinstance(key, Tensor) else key
    v = value._data if isinstance(value, Tensor) else value
    offs = np.asarray(sparse_csr_offset._data
                      if isinstance(sparse_csr_offset, Tensor)
                      else sparse_csr_offset)
    cols = np.asarray(sparse_csr_columns._data
                      if isinstance(sparse_csr_columns, Tensor)
                      else sparse_csr_columns)
    b, h, seq, d = q.shape
    mask, block_mask = _csr_masks(offs, cols, seq, 128)
    if (key_padding_mask is None and attn_mask is None
            and block_mask is not None and d % 8 == 0):
        from ..ops import pallas as _pl
        from ..core.flags import get_flag
        if _pl.on_tpu() and get_flag("FLAGS_use_pallas_attention"):
            from ..ops.pallas.block_sparse_attention import \
                block_sparse_attention_pallas
            qs = jnp.einsum("bhsd->bshd", q)
            ks = jnp.einsum("bhsd->bshd", k)
            vs = jnp.einsum("bhsd->bshd", v)
            out = block_sparse_attention_pallas(qs, ks, vs, block_mask)
            return Tensor(jnp.einsum("bshd->bhsd", out))
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    scores = jnp.where(jnp.asarray(mask), scores, -1e9)
    probs = jax.nn.softmax(scores, axis=-1)
    # empty CSR rows output zero (the kernel's l=0 semantics)
    row_live = jnp.asarray(mask.any(axis=-1))
    probs = jnp.where(row_live[..., None], probs, 0.0)
    return Tensor(jnp.einsum("bhqk,bhkd->bhqd", probs, v))


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@defop()
def dice_loss(input, label, epsilon=1e-5):
    """1 - 2|X∩Y| / (|X|+|Y|) over one-hot labels (ref dice_loss)."""
    n_cls = input.shape[-1]
    oh = jax.nn.one_hot(label.squeeze(-1), n_cls, dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inter = jnp.sum(input * oh, axis=reduce_dims)
    union = jnp.sum(input, axis=reduce_dims) + jnp.sum(oh, axis=reduce_dims)
    return jnp.mean(1.0 - 2.0 * inter / (union + epsilon))


@defop()
def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean"):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        stirling = (label * jnp.log(label + (label == 0))
                    - label + 0.5 * jnp.log(2 * jnp.pi * (label + (label == 0))))
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


@defop()
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Improved N-pair loss (Sohn 2016; ref npair_loss)."""
    reg = l2_reg * (jnp.mean(jnp.sum(anchor * anchor, 1))
                    + jnp.mean(jnp.sum(positive * positive, 1))) * 0.25
    sim = anchor @ positive.T
    eq = (labels[:, None] == labels[None, :]).astype(sim.dtype)
    tgt = eq / jnp.sum(eq, axis=1, keepdims=True)
    xent = -jnp.sum(tgt * jax.nn.log_softmax(sim, axis=1), axis=1)
    xent_t = -jnp.sum(tgt * jax.nn.log_softmax(sim.T, axis=1), axis=1)
    return jnp.mean(xent) / 2 + jnp.mean(xent_t) / 2 + reg


@defop()
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    p = jax.nn.sigmoid(logit)
    ce = -(label * jax.nn.log_sigmoid(logit)
           + (1 - label) * jax.nn.log_sigmoid(-logit))
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * ((1 - p_t) ** gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


@defop()
def soft_margin_loss(input, label, reduction="mean"):
    loss = jnp.log1p(jnp.exp(-label * input))
    return _reduce(loss, reduction)


@defop()
def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean"):
    n, c = input.shape
    correct = jnp.take_along_axis(input, label[:, None].astype(jnp.int32), 1)
    m = jnp.maximum(0.0, margin - correct + input) ** p
    if weight is not None:
        m = m * weight[label.astype(jnp.int32)][:, None]
    mask = jax.nn.one_hot(label, c, dtype=input.dtype)
    loss = jnp.sum(m * (1 - mask), axis=1) / c
    return _reduce(loss, reduction)


@defop()
def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean"):
    loss = -(label * jax.nn.log_sigmoid(input)
             + (1 - label) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        loss = loss * weight
    loss = jnp.mean(loss, axis=-1)
    return _reduce(loss, reduction)


@defop()
def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean"):
    cos = jnp.sum(input1 * input2, -1) / (
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1)
        + 1e-12)
    loss = jnp.where(label == 1, 1 - cos,
                     jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


@defop()
def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    var = jnp.maximum(variance, epsilon)
    loss = 0.5 * (jnp.log(var) + (input - label) ** 2 / var)
    if full:
        loss = loss + 0.5 * jnp.log(2 * jnp.asarray(jnp.pi))
    return _reduce(loss, reduction)


@defop()
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    def dist(a, b):
        return jnp.sum(jnp.abs(a - b + epsilon) ** p, -1) ** (1 / p)
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dn = jnp.minimum(dn, dist(positive, negative))
    return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        from ..ops import minimum
        dn = minimum(dn, distance_function(positive, negative))
    from ..ops import clip, maximum
    from .functional import relu
    loss = relu(dp - dn + margin)
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


@defop()
def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False):
    """Hierarchical sigmoid loss (ref hsigmoid_loss; phi
    hierarchical_sigmoid kernel). Default: complete-binary-tree coding.
    Custom trees: path_table [N, L] holds each sample's internal-node walk
    (entries < 0 are padding) and path_code [N, L] the 0/1 branch codes —
    the reference's is_custom Huffman-tree path."""

    def _walk_loss(nodes, codes):
        valid = nodes >= 0
        w = weight[jnp.maximum(nodes, 0)]     # [N, L, D]
        logits = jnp.einsum("nd,nkd->nk", input, w)
        if bias is not None:
            logits_b = logits + bias.reshape(-1)[jnp.maximum(nodes, 0)]
        else:
            logits_b = logits
        ce = -(codes * jax.nn.log_sigmoid(logits_b)
               + (1 - codes) * jax.nn.log_sigmoid(-logits_b))
        return jnp.sum(jnp.where(valid, ce, 0.0), -1, keepdims=True)

    if path_table is not None or path_code is not None:
        if path_table is None or path_code is None:
            raise ValueError(
                "custom-tree hsigmoid needs BOTH path_table and path_code")
        return _walk_loss(path_table.astype(jnp.int32),
                          path_code.astype(input.dtype))
    code_len = int(np.ceil(np.log2(num_classes)))
    lab = label.astype(jnp.int32)
    # node index walk of the complete binary tree: internal nodes 0..C-2
    codes = []
    nodes = []
    cur = lab + num_classes - 1          # leaf position in the heap
    for _ in range(code_len):
        parent = (cur - 1) // 2
        codes.append((cur % 2 == 1).astype(input.dtype))  # left=1 like ref
        nodes.append(parent)
        cur = parent
    return _walk_loss(jnp.stack(nodes, -1), jnp.stack(codes, -1))


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-style margin softmax (ref margin_cross_entropy)."""
    from ..ops.registry import dispatch

    def _impl(logits, label):
        lab = label.astype(jnp.int32)
        theta = jnp.arccos(jnp.clip(
            jnp.take_along_axis(logits, lab[:, None], 1), -1 + 1e-7,
            1 - 1e-7))
        target = jnp.cos(margin1 * theta + margin2) - margin3
        oh = jax.nn.one_hot(lab, logits.shape[-1], dtype=logits.dtype)
        adjusted = logits * (1 - oh) + target * oh
        adjusted = adjusted * scale
        logp = jax.nn.log_softmax(adjusted, axis=-1)
        loss = -jnp.take_along_axis(logp, lab[:, None], 1)
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jnp.exp(logp)
        return loss

    return dispatch(_impl, (logits, label), {},
                    op_name="margin_cross_entropy")


# ---------------------------------------------------------------------------
# CTC / RNN-T
# ---------------------------------------------------------------------------

_NEG_INF = -1e30


@defop()
def _ctc_loss_impl(log_probs, labels, input_lengths, label_lengths, blank):
    """CTC forward (log space) via lax.scan over time.

    log_probs: [T, B, C] log-softmax outputs; labels: [B, L] int.
    Standard extended-label alpha recursion (Graves 2006).
    """
    log_probs = jax.nn.log_softmax(log_probs, axis=-1)
    T, B, C = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    lab = labels.astype(jnp.int32)
    # extended label sequence: blank interleaved
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    # allow skip when ext[s] != ext[s-2] and not blank
    skip_ok = jnp.zeros((B, S), bool)
    skip_ok = skip_ok.at[:, 2:].set(
        (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2]))

    def emit(t_probs):                     # [B, C] -> [B, S]
        return jnp.take_along_axis(t_probs, ext, axis=1)

    alpha0 = jnp.full((B, S), _NEG_INF)
    alpha0 = alpha0.at[:, 0].set(log_probs[0, jnp.arange(B), blank])
    first_lab = jnp.take_along_axis(log_probs[0], ext[:, 1:2], 1)[:, 0]
    alpha0 = alpha0.at[:, 1].set(jnp.where(L > 0, first_lab, _NEG_INF))

    def step(alpha, t_probs):
        shift1 = jnp.concatenate(
            [jnp.full((B, 1), _NEG_INF), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate(
            [jnp.full((B, 2), _NEG_INF), alpha[:, :-2]], axis=1)
        shift2 = jnp.where(skip_ok, shift2, _NEG_INF)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
        new_alpha = merged + emit(t_probs)
        return new_alpha, new_alpha

    _, alphas = jax.lax.scan(step, alpha0, log_probs[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, S]

    # per-sample final alpha at t = input_length - 1,
    # summed over last blank and last label positions
    t_idx = (input_lengths.astype(jnp.int32) - 1)
    final = alphas[t_idx, jnp.arange(B)]          # [B, S]
    s_last = 2 * label_lengths.astype(jnp.int32)  # last blank position
    a_blank = jnp.take_along_axis(final, s_last[:, None], 1)[:, 0]
    a_label = jnp.take_along_axis(
        final, jnp.maximum(s_last - 1, 0)[:, None], 1)[:, 0]
    a_label = jnp.where(label_lengths > 0, a_label, _NEG_INF)
    return -jnp.logaddexp(a_blank, a_label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """paddle.nn.functional.ctc_loss (ref loss.py ctc_loss over warpctc).
    log_probs [T, B, C] (logits accepted: log_softmax applied)."""
    loss = _ctc_loss_impl(log_probs, labels, input_lengths, label_lengths,
                          blank)
    if norm_by_times:
        loss = loss / input_lengths.astype("float32")
    if reduction == "mean":
        return (loss / label_lengths.astype("float32")).mean()
    if reduction == "sum":
        return loss.sum()
    return loss


@defop()
def _rnnt_loss_impl(logits, labels, input_lengths, label_lengths, blank):
    """RNN-T alpha recursion (Graves 2012). logits: [B, T, U+1, C]."""
    B, T, U1, C = logits.shape
    lp = jax.nn.log_softmax(logits, axis=-1)
    lab = labels.astype(jnp.int32)                      # [B, U]
    blank_lp = lp[..., blank]                           # [B, T, U+1]
    emit_lp = jnp.take_along_axis(
        lp[:, :, :U1 - 1, :],
        jnp.broadcast_to(lab[:, None, :, None], (B, T, U1 - 1, 1)),
        axis=-1)[..., 0]                                # [B, T, U]

    def u_scan(alpha_row_prev, inputs):
        """row t: alpha[t, u] from alpha[t-1, u] (blank) and alpha[t, u-1]
        (emit); the emit term is a sequential scan along u."""
        from_blank, emit_row = inputs    # [B, U+1], [B, U]

        def cell(carry, xs):
            fb_u, em_prev = xs           # [B], [B]
            a = jnp.logaddexp(fb_u, carry + em_prev)
            return a, a

        init = from_blank[:, 0]
        _, rest = jax.lax.scan(
            cell, init,
            (jnp.moveaxis(from_blank[:, 1:], 1, 0),
             jnp.moveaxis(emit_row, 1, 0)))
        return jnp.concatenate([init[:, None],
                                jnp.moveaxis(rest, 0, 1)], axis=1)

    alpha = u_scan(None, (jnp.concatenate(
        [jnp.zeros((B, 1)), jnp.full((B, U1 - 1), _NEG_INF)], 1),
        emit_lp[:, 0]))
    rows = [alpha]
    for t in range(1, T):
        from_blank = alpha + blank_lp[:, t - 1]
        alpha = u_scan(None, (from_blank, emit_lp[:, t]))
        rows.append(alpha)
    alphas = jnp.stack(rows, axis=1)       # [B, T, U+1]

    t_idx = input_lengths.astype(jnp.int32) - 1
    u_idx = label_lengths.astype(jnp.int32)
    final = alphas[jnp.arange(B), t_idx, u_idx]
    final_blank = blank_lp[jnp.arange(B), t_idx, u_idx]
    return -(final + final_blank)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean"):
    """paddle.nn.functional.rnnt_loss (ref over warp-transducer)."""
    loss = _rnnt_loss_impl(input, label, input_lengths, label_lengths, blank)
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


# ---------------------------------------------------------------------------
# spatial transformer
# ---------------------------------------------------------------------------

@defop()
def affine_grid(theta, out_shape, align_corners=True):
    """theta [N, 2, 3] -> sampling grid [N, H, W, 2] (ref affine_grid)."""
    n, _, h, w = (out_shape[0], out_shape[1], out_shape[2], out_shape[3])

    def lin(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    ys = lin(h)
    xs = lin(w)
    gx, gy = jnp.meshgrid(xs, ys)
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(1, h * w, 3)
    grid = jnp.einsum("nhk,nok->nho", jnp.broadcast_to(base, (n, h * w, 3)),
                      theta)
    return grid.reshape(n, h, w, 2)


@defop()
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """x [N, C, H, W], grid [N, Hg, Wg, 2] in [-1, 1] (ref grid_sample)."""
    n, c, h, w = x.shape
    gx = grid[..., 0]
    gy = grid[..., 1]
    if align_corners:
        fx = (gx + 1) * (w - 1) / 2
        fy = (gy + 1) * (h - 1) / 2
    else:
        fx = ((gx + 1) * w - 1) / 2
        fy = ((gy + 1) * h - 1) / 2

    def sample(ix, iy):
        valid = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
        if padding_mode == "border":
            ix = jnp.clip(ix, 0, w - 1)
            iy = jnp.clip(iy, 0, h - 1)
            valid = jnp.ones_like(valid)
        elif padding_mode == "reflection":
            ix = jnp.abs(ix)
            ix = jnp.where(ix > w - 1, 2 * (w - 1) - ix, ix)
            iy = jnp.abs(iy)
            iy = jnp.where(iy > h - 1, 2 * (h - 1) - iy, iy)
            valid = jnp.ones_like(valid)
        ixc = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
        iyc = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
        vals = x[jnp.arange(n)[:, None, None], :, iyc, ixc]  # [N,Hg,Wg,C]
        return jnp.where(valid[..., None], vals, 0.0)

    if mode == "nearest":
        out = sample(jnp.round(fx), jnp.round(fy))
    else:
        x0 = jnp.floor(fx)
        y0 = jnp.floor(fy)
        x1 = x0 + 1
        y1 = y0 + 1
        wa = (x1 - fx) * (y1 - fy)
        wb = (x1 - fx) * (fy - y0)
        wc = (fx - x0) * (y1 - fy)
        wd = (fx - x0) * (fy - y0)
        out = (sample(x0, y0) * wa[..., None] + sample(x0, y1) * wb[..., None]
               + sample(x1, y0) * wc[..., None]
               + sample(x1, y1) * wd[..., None])
    return jnp.moveaxis(out, -1, 1)        # [N, C, Hg, Wg]


__all__ = [
    "max_pool3d", "avg_pool3d", "adaptive_avg_pool3d", "adaptive_max_pool1d",
    "adaptive_max_pool3d", "max_unpool1d", "max_unpool2d", "max_unpool3d",
    "fractional_max_pool2d", "fractional_max_pool3d", "conv1d_transpose",
    "conv3d_transpose", "log_sigmoid", "zeropad2d", "pixel_unshuffle",
    "channel_shuffle", "sequence_mask", "gather_tree", "class_center_sample",
    "sparse_attention", "dice_loss", "poisson_nll_loss", "npair_loss",
    "sigmoid_focal_loss", "soft_margin_loss", "multi_margin_loss",
    "multi_label_soft_margin_loss", "cosine_embedding_loss",
    "gaussian_nll_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "hsigmoid_loss",
    "margin_cross_entropy", "ctc_loss", "rnnt_loss", "affine_grid",
    "grid_sample",
]
