"""Parameter initializers.

Analog of python/paddle/nn/initializer/* (XavierInitializer, MSRAInitializer,
Normal/Uniform/Constant/Assign/TruncatedNormal). An initializer is a callable
(shape, dtype) -> jnp array drawn from the global generator (core/random.py).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core import random as random_mod


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=None):
        return jnp.full(tuple(shape), self.value,
                        dtype_mod.to_jax_dtype(dtype) or dtype_mod.get_default_dtype())


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        dt = dtype_mod.to_jax_dtype(dtype) or dtype_mod.get_default_dtype()
        return self.mean + self.std * jax.random.normal(
            random_mod.next_key(), tuple(shape), dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype=None):
        dt = dtype_mod.to_jax_dtype(dtype) or dtype_mod.get_default_dtype()
        return self.mean + self.std * jax.random.truncated_normal(
            random_mod.next_key(), self.a, self.b, tuple(shape), dt)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None):
        dt = dtype_mod.to_jax_dtype(dtype) or dtype_mod.get_default_dtype()
        return jax.random.uniform(random_mod.next_key(), tuple(shape), dt,
                                  self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        return Normal(0.0, gain / math.sqrt(fi))(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=None):
        dt = dtype_mod.to_jax_dtype(dtype) or dtype_mod.get_default_dtype()
        arr = jnp.asarray(np.asarray(
            self.value.numpy() if hasattr(self.value, "numpy") else self.value), dt)
        return arr.reshape(tuple(shape))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None):
        dt = dtype_mod.to_jax_dtype(dtype) or dtype_mod.get_default_dtype()
        return jax.nn.initializers.orthogonal(self.gain)(
            random_mod.next_key(), tuple(shape), dt)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        dt = dtype_mod.to_jax_dtype(dtype) or dtype_mod.get_default_dtype()
        arr = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                idx = (g * (oc // self.groups) + i, i) + tuple(centers)
                arr[idx] = 1.0
        return jnp.asarray(arr, dt)


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv2d": 1.0,
             "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             "selu": 3.0 / 4.0}
    return gains[nonlinearity]


class Bilinear(Initializer):
    """ref nn/initializer/Bilinear: upsampling-kernel init for transposed
    convs (weight [C_out, C_in, k, k])."""

    def __call__(self, shape, dtype):
        import numpy as _np
        w = _np.zeros(shape, dtype="float32")
        k = shape[-1]
        f = int(_np.ceil(k / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for flat in range(_np.prod(shape[-2:])):
            x = flat % k
            y = (flat // k) % k
            val = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            w[..., y, x] = val
        return jnp.asarray(w, dtype)


_GLOBAL_INIT = [None, None]   # (weight_init, bias_init)


def set_global_initializer(weight_init, bias_init=None):
    """ref nn/initializer/set_global_initializer: default initializers for
    subsequently created parameters (Layer.create_parameter consults this
    when no attr/default is given)."""
    _GLOBAL_INIT[0] = weight_init
    _GLOBAL_INIT[1] = bias_init


def get_global_initializer(is_bias=False):
    return _GLOBAL_INIT[1 if is_bias else 0]
