"""nn layer long tail: 3-D pool/conv layers, loss layers, decode infra.

Reference: python/paddle/nn/layer/{pooling,conv,norm,loss,common,vision,
rnn}.py — each class is the thin parameter/config holder over the
functional surface (functional_extras.py), matching paddle constructor
signatures.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import functional as F
from . import initializer as I
from .conv import _ConvNd
from .layer import Layer


# ---------------------------------------------------------------------------
# conv transpose layers
# ---------------------------------------------------------------------------

class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


# ---------------------------------------------------------------------------
# pooling layers
# ---------------------------------------------------------------------------

class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCDHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, return_mask,
                     data_format)

    def forward(self, x):
        k, s, p, cm, rm, df = self.args
        return F.max_pool3d(x, k, s, p, cm, rm, df)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override, data_format)

    def forward(self, x):
        k, s, p, cm, ex, d, df = self.args
        return F.avg_pool3d(x, k, s, p, cm, ex, d, df)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, os = self.args
        return F.max_unpool1d(x, indices, k, s, p, df, os)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, os = self.args
        return F.max_unpool2d(x, indices, k, s, p, df, os)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, os = self.args
        return F.max_unpool3d(x, indices, k, s, p, df, os)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        o, k, u, rm = self.args
        return F.fractional_max_pool2d(x, o, k, u, rm)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        o, k, u, rm = self.args
        return F.fractional_max_pool3d(x, o, k, u, rm)


# ---------------------------------------------------------------------------
# norm / padding / misc feature layers
# ---------------------------------------------------------------------------

class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.weight = self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size,
                             scale_factor=self.scale_factor, mode="nearest",
                             data_format=self.data_format)


class UpsamplingBilinear2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size,
                             scale_factor=self.scale_factor, mode="bilinear",
                             align_corners=True,
                             data_format=self.data_format)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__()
        self.args = (padding, mode, value, data_format)

    def forward(self, x):
        p, m, v, df = self.args
        return F.pad(x, p, mode=m, value=v, data_format=df)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__()
        self.args = (padding, mode, value, data_format)

    def forward(self, x):
        p, m, v, df = self.args
        return F.pad(x, p, mode=m, value=v, data_format=df)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.target = list(shape)

    def forward(self, x):
        shape = list(x.shape)
        axis = self.axis % len(shape)
        new_shape = shape[:axis] + self.target + shape[axis + 1:]
        return x.reshape(new_shape)


class Softmax2D(Layer):
    """Softmax over channels of NCHW input (ref Softmax2D)."""

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError("Softmax2D expects 3D/4D input")
        return F.softmax(x, axis=-3)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, axis=self.axis)


class Silu(Layer):
    def forward(self, x):
        return F.silu(x)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, x):
        return F.rrelu(x, lower=self.lower, upper=self.upper,
                       training=self.training)


# ---------------------------------------------------------------------------
# loss layers
# ---------------------------------------------------------------------------

def _loss_layer(name, fn_name, arg_names, defaults):
    """Factory for the thin loss layers: ctor stores config, forward calls
    the functional with stored kwargs."""

    def __init__(self, **kwargs):
        Layer.__init__(self)
        self._cfg = dict(defaults)
        for k, v in kwargs.items():
            if k in ("name",):
                continue
            if k not in self._cfg:
                raise TypeError(f"{name}: unexpected argument {k}")
            self._cfg[k] = v

    def forward(self, *args):
        fn = getattr(F, fn_name)
        return fn(*args, **self._cfg)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


PoissonNLLLoss = _loss_layer(
    "PoissonNLLLoss", "poisson_nll_loss", None,
    {"log_input": True, "full": False, "epsilon": 1e-8, "reduction": "mean"})
SoftMarginLoss = _loss_layer(
    "SoftMarginLoss", "soft_margin_loss", None, {"reduction": "mean"})
MultiMarginLoss = _loss_layer(
    "MultiMarginLoss", "multi_margin_loss", None,
    {"p": 1, "margin": 1.0, "weight": None, "reduction": "mean"})
MultiLabelSoftMarginLoss = _loss_layer(
    "MultiLabelSoftMarginLoss", "multi_label_soft_margin_loss", None,
    {"weight": None, "reduction": "mean"})
CosineEmbeddingLoss = _loss_layer(
    "CosineEmbeddingLoss", "cosine_embedding_loss", None,
    {"margin": 0.0, "reduction": "mean"})
GaussianNLLLoss = _loss_layer(
    "GaussianNLLLoss", "gaussian_nll_loss", None,
    {"full": False, "epsilon": 1e-6, "reduction": "mean"})
TripletMarginLoss = _loss_layer(
    "TripletMarginLoss", "triplet_margin_loss", None,
    {"margin": 1.0, "p": 2.0, "epsilon": 1e-6, "swap": False,
     "reduction": "mean"})
TripletMarginWithDistanceLoss = _loss_layer(
    "TripletMarginWithDistanceLoss", "triplet_margin_with_distance_loss",
    None, {"distance_function": None, "margin": 1.0, "swap": False,
           "reduction": "mean"})


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.is_custom = is_custom
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([num_classes - 1, 1],
                                          attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        has_paths = path_table is not None and path_code is not None
        if self.is_custom and not has_paths:
            raise ValueError(
                "HSigmoidLoss(is_custom=True) requires path_table and "
                "path_code at every forward (reference semantics)")
        if not self.is_custom and (path_table is not None
                                   or path_code is not None):
            raise ValueError(
                "path_table/path_code need HSigmoidLoss(is_custom=True)")
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)


# ---------------------------------------------------------------------------
# decode infra: BeamSearchDecoder + dynamic_decode
# ---------------------------------------------------------------------------

class BeamSearchDecoder:
    """Beam-search decoder over an RNN cell (ref nn/decode.py
    BeamSearchDecoder): host-driven beam bookkeeping over jnp scores — the
    idiomatic TPU form keeps the cell step compiled and the beam reshuffle
    as gathers."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        """Tile states to [B*beam, ...]; first step only beam 0 is live."""
        import jax

        def tile(t):
            a = t._data if isinstance(t, Tensor) else jnp.asarray(t)
            rep = jnp.repeat(a, self.beam_size, axis=0)
            return Tensor(rep)

        states = jax.tree_util.tree_map(
            tile, initial_cell_states,
            is_leaf=lambda x: isinstance(x, Tensor))
        batch = (initial_cell_states[0].shape[0]
                 if isinstance(initial_cell_states, (list, tuple))
                 else initial_cell_states.shape[0])
        ids = jnp.full((batch * self.beam_size,), self.start_token,
                       jnp.int32)
        # log-prob 0 for beam 0, -inf others so step 1 expands one beam
        lp = jnp.tile(jnp.asarray([0.0] + [-1e9] * (self.beam_size - 1)),
                      (batch,))
        finished = jnp.zeros((batch * self.beam_size,), bool)
        return Tensor(ids), states, Tensor(lp), Tensor(finished)

    def step(self, inputs, states):
        if self.embedding_fn is not None:
            inputs = self.embedding_fn(inputs)
        out, new_states = self.cell(inputs, states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        return out, new_states


def dynamic_decode(decoder, inits=None, max_step_num=32, **kwargs):
    """ref nn/decode.py dynamic_decode: run the decoder to completion.

    Returns (ids [B, beam, T], final_scores [B, beam]).
    """
    import jax

    ids_t, states, lp_t, fin_t = decoder.initialize(inits)
    beam = decoder.beam_size
    batch = ids_t.shape[0] // beam
    lp = lp_t._data
    finished = fin_t._data
    tokens = ids_t
    all_ids = []
    for _ in range(max_step_num):
        logits, states = decoder.step(tokens, states)
        logp = jax.nn.log_softmax(
            logits._data if isinstance(logits, Tensor) else logits, -1)
        vocab = logp.shape[-1]
        # finished beams only extend with end_token at no cost
        end_mask = jnp.full((vocab,), -1e9).at[decoder.end_token].set(0.0)
        logp = jnp.where(finished[:, None], end_mask[None, :], logp)
        total = lp[:, None] + logp                       # [B*beam, V]
        total = total.reshape(batch, beam * vocab)
        top_lp, top_idx = jax.lax.top_k(total, beam)     # [B, beam]
        beam_src = top_idx // vocab
        token = (top_idx % vocab).astype(jnp.int32)
        flat_src = (jnp.arange(batch)[:, None] * beam + beam_src).reshape(-1)

        def regather(t):
            a = t._data if isinstance(t, Tensor) else jnp.asarray(t)
            return Tensor(a[flat_src])

        states = jax.tree_util.tree_map(
            regather, states, is_leaf=lambda x: isinstance(x, Tensor))
        lp = top_lp.reshape(-1)
        tokens = Tensor(token.reshape(-1))
        finished = finished[flat_src] | (token.reshape(-1)
                                         == decoder.end_token)
        all_ids.append(token)
        if bool(finished.all()):
            break
    ids = jnp.stack(all_ids, axis=-1)                    # [B, beam, T]
    return Tensor(ids), Tensor(lp.reshape(batch, beam))


__all__ = [
    "Conv1DTranspose", "Conv3DTranspose", "MaxPool3D", "AvgPool3D",
    "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool3D",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D", "FractionalMaxPool2D",
    "FractionalMaxPool3D", "InstanceNorm1D", "InstanceNorm3D",
    "UpsamplingNearest2D", "UpsamplingBilinear2D", "Pad1D", "Pad3D",
    "Dropout3D", "PixelUnshuffle", "ChannelShuffle", "Unflatten",
    "Softmax2D", "GLU", "Silu", "RReLU", "PoissonNLLLoss", "SoftMarginLoss",
    "MultiMarginLoss", "MultiLabelSoftMarginLoss", "CosineEmbeddingLoss",
    "GaussianNLLLoss", "TripletMarginLoss", "TripletMarginWithDistanceLoss",
    "CTCLoss", "RNNTLoss", "HSigmoidLoss", "SpectralNorm",
    "BeamSearchDecoder", "dynamic_decode",
]
