"""nn functional ops.

Analog of python/paddle/nn/functional/* — conv/pool/norm/embedding/loss/attention.
Convs and attention lower to single XLA ops (conv_general_dilated, dot_general)
so the MXU gets large fused contractions (replacing cuDNN dispatch in
phi/kernels/gpudnn and fused kernels in phi/kernels/fusion).
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core import random as random_mod
from ..core.tensor import Tensor
from ..ops.registry import defop
from ..ops import activation as _act

# re-export activations into functional namespace (paddle.nn.functional.relu etc.)
from ..ops.activation import (relu, relu6, leaky_relu, prelu, elu, selu, celu,
                              gelu, silu, swish, mish, hardswish, hardsigmoid,
                              hardtanh, hardshrink, softshrink, tanhshrink,
                              softplus, softsign, softmax, log_softmax,
                              gumbel_softmax, glu, maxout, rrelu,
                              thresholded_relu)  # noqa: F401
from ..ops.math import sigmoid, tanh  # noqa: F401
from ..ops.manipulation import pad  # noqa: F401


@defop()
def linear(x, weight, bias=None):
    """paddle.nn.functional.linear: weight is [in_features, out_features]."""
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


# -- convolution ------------------------------------------------------------

def _conv_padding(padding, spatial, strides=None, dilations=None):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * spatial
    padding = list(padding)
    if builtins.all(isinstance(p, int) for p in padding):
        if len(padding) == spatial:
            return [(p, p) for p in padding]
        if len(padding) == 2 * spatial:
            return [(padding[2 * i], padding[2 * i + 1]) for i in range(spatial)]
    return [tuple(p) for p in padding]


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


@defop()
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    """Conv2D over XLA conv_general_dilated (ref: phi/kernels/gpudnn/conv_kernel.cu).
    weight layout [out_c, in_c/groups, kh, kw] (paddle OIHW)."""
    lhs_spec = data_format
    out_spec = data_format
    dn = jax.lax.conv_dimension_numbers(x.shape, weight.shape,
                                        (lhs_spec, "OIHW", out_spec))
    out = jax.lax.conv_general_dilated(
        x, weight,
        window_strides=_tuple(stride, 2),
        padding=_conv_padding(padding, 2),
        rhs_dilation=_tuple(dilation, 2),
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None)
    if bias is not None:
        shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
        out = out + bias.reshape(shape)
    return out


@defop()
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    spec = {"NCL": "NCH", "NLC": "NHC"}[data_format]
    dn = jax.lax.conv_dimension_numbers(x.shape, weight.shape, (spec, "OIH", spec))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=_tuple(stride, 1),
        padding=_conv_padding(padding, 1), rhs_dilation=_tuple(dilation, 1),
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        shape = [1, -1, 1] if data_format == "NCL" else [1, 1, -1]
        out = out + bias.reshape(shape)
    return out


@defop()
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    dn = jax.lax.conv_dimension_numbers(x.shape, weight.shape,
                                        (data_format, "OIDHW", data_format))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=_tuple(stride, 3),
        padding=_conv_padding(padding, 3), rhs_dilation=_tuple(dilation, 3),
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        shape = [1, -1, 1, 1, 1] if data_format == "NCDHW" else [1, 1, 1, 1, -1]
        out = out + bias.reshape(shape)
    return out


@defop()
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     output_size=None, data_format="NCHW"):
    """weight layout [in_c, out_c/groups, kh, kw] (paddle IOHW for transpose)."""
    stride = _tuple(stride, 2)
    dilation = _tuple(dilation, 2)
    pad2 = _conv_padding(padding, 2)
    if isinstance(pad2, str):
        padcfg = pad2
    else:
        # transpose conv padding: XLA wants the gradient-style padding
        kh = (weight.shape[2] - 1) * dilation[0] + 1
        kw = (weight.shape[3] - 1) * dilation[1] + 1
        opad = _tuple(output_padding, 2)
        padcfg = [(kh - 1 - pad2[0][0], kh - 1 - pad2[0][1] + opad[0]),
                  (kw - 1 - pad2[1][0], kw - 1 - pad2[1][1] + opad[1])]
    # IOHW -> flip spatial, swap io -> use as OIHW with transposed feature dims
    w = jnp.flip(weight, axis=(2, 3))
    if groups > 1:
        ic, ocg = w.shape[0], w.shape[1]
        w = w.reshape(groups, ic // groups, ocg, *w.shape[2:])
        w = jnp.swapaxes(w, 1, 2).reshape(groups * ocg, ic // groups, *w.shape[3:])
    else:
        w = jnp.swapaxes(w, 0, 1)
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                        (data_format, "OIHW", data_format))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=padcfg,
        lhs_dilation=stride, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
        out = out + bias.reshape(shape)
    return out


# -- pooling ----------------------------------------------------------------

def _pool_dims(data_format, kernel, stride, padding, nd=2, x_shape=None,
               ceil_mode=False):
    kernel = _tuple(kernel, nd)
    stride = _tuple(stride if stride is not None else kernel, nd)
    spatial_pads = list(_conv_padding(padding, nd))
    if ceil_mode and x_shape is not None:
        # extend the high-side padding so the last partial window is kept
        # (padding in reduce_window fills with the reduction identity)
        if data_format in ("NCHW", "NCL", "NCDHW"):
            spatial_sizes = x_shape[2:2 + nd]
        else:
            spatial_sizes = x_shape[1:1 + nd]
        new_pads = []
        for size, k, s, (pl, ph) in zip(spatial_sizes, kernel, stride,
                                        spatial_pads):
            eff = size + pl + ph
            out_ceil = -(-(eff - k) // s) + 1
            need = (out_ceil - 1) * s + k - eff
            new_pads.append((pl, ph + builtins.max(need, 0)))
        spatial_pads = new_pads
    if data_format in ("NCHW", "NCL", "NCDHW"):
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = [(0, 0), (0, 0)] + spatial_pads
    else:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = [(0, 0)] + spatial_pads + [(0, 0)]
    return window, strides, pads


def _max_pool_mask(x, window, strides, pads):
    """Flattened-spatial argmax indices per pooling window (paddle mask
    semantics for return_mask=True). Static unroll over the (small) kernel
    offsets; NC-leading layouts."""
    import itertools

    kernel = window[2:]
    stride = strides[2:]
    spatial_pads = pads[2:]
    lead = x.shape[:2]
    spatial = x.shape[2:]
    xp = jnp.pad(x, [(0, 0), (0, 0)] + list(spatial_pads),
                 constant_values=-jnp.inf)
    flat = jnp.arange(int(np.prod(spatial)), dtype=jnp.int32).reshape(
        (1, 1) + spatial)
    flat = jnp.broadcast_to(flat, x.shape)
    fp = jnp.pad(flat, [(0, 0), (0, 0)] + list(spatial_pads),
                 constant_values=-1)
    out_spatial = tuple(
        (s + pl + ph - k) // st + 1
        for s, k, st, (pl, ph) in zip(spatial, kernel, stride, spatial_pads))
    vals, idxs = [], []
    for offs in itertools.product(*[range(k) for k in kernel]):
        starts = (0, 0) + offs
        limits = lead + tuple(
            o + (os - 1) * st + 1
            for o, os, st in zip(offs, out_spatial, stride))
        sl_strides = (1, 1) + stride
        vals.append(jax.lax.slice(xp, starts, limits, sl_strides))
        idxs.append(jax.lax.slice(fp, starts, limits, sl_strides))
    V = jnp.stack(vals, axis=-1)
    I = jnp.stack(idxs, axis=-1)
    am = jnp.argmax(V, axis=-1)
    return jnp.take_along_axis(I, am[..., None], axis=-1)[..., 0]


@defop()
def _max_pool(x, window, strides, pads):
    neg = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.iinfo(x.dtype).min
    return jax.lax.reduce_window(x, neg, jax.lax.max, window, strides, pads)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW"):
    window, strides, pads = _pool_dims(data_format, kernel_size, stride,
                                       padding, 2, tuple(x.shape), ceil_mode)
    out = _max_pool(x, window, strides, pads)
    if return_mask:
        mask = Tensor(_max_pool_mask(x._data, window, strides, pads))
        return out, mask
    return out


@defop()
def _avg_pool(x, window, strides, pads, exclusive, divisor_override):
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
    if divisor_override:
        return summed / divisor_override
    if exclusive:
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                       pads)
        return summed / counts
    return summed / float(np.prod(window))


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW"):
    window, strides, pads = _pool_dims(data_format, kernel_size, stride,
                                       padding, 2, tuple(x.shape), ceil_mode)
    return _avg_pool(x, window, strides, pads, exclusive, divisor_override)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL"):
    window, strides, pads = _pool_dims(data_format, kernel_size, stride,
                                       padding, 1, tuple(x.shape), ceil_mode)
    out = _max_pool(x, window, strides, pads)
    if return_mask:
        mask = Tensor(_max_pool_mask(x._data, window, strides, pads))
        return out, mask
    return out


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL"):
    window, strides, pads = _pool_dims(data_format, kernel_size, stride,
                                       padding, 1, tuple(x.shape), ceil_mode)
    return _avg_pool(x, window, strides, pads, exclusive, None)


@defop()
def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    oh, ow = _tuple(output_size, 2)
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))
    n, c, h, w = x.shape
    if h % oh == 0 and w % ow == 0:
        out = x.reshape(n, c, oh, h // oh, ow, w // ow).mean(axis=(3, 5))
    else:
        # general case: per-output-cell slicing with static bounds
        rows = []
        for i in range(oh):
            h0, h1 = (i * h) // oh, -(-((i + 1) * h) // oh)
            cols = []
            for j in range(ow):
                w0, w1 = (j * w) // ow, -(-((j + 1) * w) // ow)
                cols.append(x[:, :, h0:h1, w0:w1].mean(axis=(2, 3)))
            rows.append(jnp.stack(cols, axis=-1))
        out = jnp.stack(rows, axis=-2)
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


@defop()
def adaptive_max_pool2d(x, output_size, return_mask=False, data_format="NCHW"):
    if return_mask:
        raise NotImplementedError("adaptive_max_pool2d(return_mask=True)")
    oh, ow = _tuple(output_size, 2)
    n, c, h, w = x.shape
    if h % oh == 0 and w % ow == 0:
        return x.reshape(n, c, oh, h // oh, ow, w // ow).max(axis=(3, 5))
    rows = []
    for i in range(oh):
        h0, h1 = (i * h) // oh, -(-((i + 1) * h) // oh)
        cols = []
        for j in range(ow):
            w0, w1 = (j * w) // ow, -(-((j + 1) * w) // ow)
            cols.append(x[:, :, h0:h1, w0:w1].max(axis=(2, 3)))
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


@defop()
def adaptive_avg_pool1d(x, output_size):
    n, c, l = x.shape
    o = output_size if isinstance(output_size, int) else output_size[0]
    if l % o == 0:
        return x.reshape(n, c, o, l // o).mean(axis=3)
    cols = []
    for j in range(o):
        w0, w1 = (j * l) // o, -(-((j + 1) * l) // o)
        cols.append(x[:, :, w0:w1].mean(axis=2))
    return jnp.stack(cols, axis=-1)


# -- normalization ----------------------------------------------------------

@defop()
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    # reduce in fp32 for bf16 stability (reference: layer_norm fp32 accumulators)
    xf = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@defop()
def rms_norm(x, weight=None, epsilon=1e-6):
    """RMSNorm (llama-family; ref incubate fused_rms_norm). On TPU with a
    weight, routes to the fused Pallas kernel (ops/pallas/fused_ops.py:
    single VMEM pass fwd, fused dx/dw bwd via custom_vjp); elsewhere XLA
    fuses the decomposed form."""
    if weight is not None:
        from ..core.flags import get_flag
        from ..ops import pallas as _pl
        if (_pl.on_tpu() and get_flag("FLAGS_use_pallas_rmsnorm")
                and x.shape[-1] % 128 == 0):
            from ..ops.pallas.fused_ops import rms_norm_pallas
            return rms_norm_pallas(x, weight, epsilon)
    xf = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = (xf * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    return out


@defop()
def _batch_norm_train(x, weight, bias, axes, epsilon, reduce_shape):
    xf = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    mean = jnp.mean(xf, axis=axes)
    var = jnp.var(xf, axis=axes)
    bshape = reduce_shape
    out = (xf - mean.reshape(bshape)) * jax.lax.rsqrt(var.reshape(bshape) + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight.reshape(bshape)
    if bias is not None:
        out = out + bias.reshape(bshape)
    return out, mean, var


@defop()
def _batch_norm_eval(x, running_mean, running_var, weight, bias, epsilon,
                     reduce_shape):
    bshape = reduce_shape
    out = (x - running_mean.reshape(bshape)) * \
        jax.lax.rsqrt(running_var.reshape(bshape) + epsilon)
    if weight is not None:
        out = out * weight.reshape(bshape)
    if bias is not None:
        out = out + bias.reshape(bshape)
    return out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None):
    """paddle.nn.functional.batch_norm. Updates running stats in-place when
    training (reference: phi batch_norm kernel updates mean_out/variance_out)."""
    nd = x.ndim
    if data_format in ("NCHW", "NCL", "NCDHW", "NC"):
        ch_axis = 1
    else:
        ch_axis = nd - 1
    axes = tuple(i for i in range(nd) if i != ch_axis)
    bshape = [1] * nd
    bshape[ch_axis] = -1
    use_stats = use_global_stats if use_global_stats is not None else not training
    if use_stats:
        return _batch_norm_eval(x, running_mean, running_var, weight, bias,
                                epsilon, tuple(bshape))
    out, mean, var = _batch_norm_train(x, weight, bias, axes, epsilon, tuple(bshape))
    if running_mean is not None:
        m = momentum
        new_mean = m * running_mean._data + (1 - m) * mean._data.astype(running_mean.dtype)
        n = float(np.prod([x.shape[a] for a in axes]))
        unbiased = var._data * (n / builtins.max(n - 1.0, 1.0))
        new_var = m * running_var._data + (1 - m) * unbiased.astype(running_var.dtype)
        running_mean._set_data(new_mean)
        running_var._set_data(new_var)
    return out


@defop()
def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    if data_format == "NHWC":
        x_t = jnp.moveaxis(x, -1, 1)
    else:
        x_t = x
    n, c = x_t.shape[:2]
    spatial = x_t.shape[2:]
    g = num_groups
    xf = x_t.astype(jnp.float32) if x_t.dtype in (jnp.bfloat16, jnp.float16) else x_t
    xg = xf.reshape(n, g, c // g, *spatial)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x_t.shape).astype(x_t.dtype)
    bshape = [1, c] + [1] * len(spatial)
    if weight is not None:
        out = out * weight.reshape(bshape)
    if bias is not None:
        out = out + bias.reshape(bshape)
    if data_format == "NHWC":
        out = jnp.moveaxis(out, 1, -1)
    return out


@defop()
def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW"):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        bshape = [1, -1] + [1] * (x.ndim - 2)
        out = out * weight.reshape(bshape)
    if bias is not None:
        bshape = [1, -1] + [1] * (x.ndim - 2)
        out = out + bias.reshape(bshape)
    return out


@defop()
def normalize(x, p=2, axis=1, epsilon=1e-12):
    nrm = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=True),
                    1.0 / p)
    return x / jnp.maximum(nrm, epsilon)


@defop()
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    sq = jnp.square(x)
    half = size // 2
    pads = [(0, 0)] * x.ndim
    pads[1] = (half, size - half - 1)
    sq = jnp.pad(sq, pads)
    window = [1] * x.ndim
    window[1] = size
    summed = jax.lax.reduce_window(sq, 0.0, jax.lax.add, tuple(window),
                                   (1,) * x.ndim, [(0, 0)] * x.ndim)
    return x / jnp.power(k + alpha * summed, beta)


# -- embedding / one-hot ----------------------------------------------------

@defop()
def embedding(x, weight, padding_idx=None, sparse=False):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    return out


@defop()
def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes, dtype=dtype_mod.get_default_dtype())


# -- dropout ----------------------------------------------------------------

def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        return x if mode == "upscale_in_train" else x * (1.0 - p)
    key = random_mod.next_key()
    return _dropout_op(x, p=float(p), axis=axis, mode=mode, key=key)


@defop(name="dropout")
def _dropout_op(x, p, axis, mode, key):
    shape = list(x.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape)).astype(x.dtype)
    if mode == "upscale_in_train":
        return x * mask / keep
    return x * mask


def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


# -- losses -----------------------------------------------------------------

def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@defop()
def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0):
    """softmax_with_cross_entropy analog (phi/kernels/gpu/cross_entropy_kernel.cu)."""
    nclass = input.shape[axis]
    logp = jax.nn.log_softmax(input, axis=axis) if use_softmax else jnp.log(
        jnp.maximum(input, 1e-30))
    if soft_label:
        soft = label
        loss = -jnp.sum(soft * logp, axis=axis)
        return _reduce(loss, reduction)
    lbl = label
    if lbl.ndim == input.ndim and lbl.shape[axis] == 1:
        lbl = jnp.squeeze(lbl, axis=axis)
    lbl = lbl.astype(jnp.int32)
    valid = (lbl != ignore_index)
    safe_lbl = jnp.where(valid, lbl, 0)
    oh = jax.nn.one_hot(safe_lbl, nclass, axis=axis, dtype=logp.dtype)
    if label_smoothing > 0.0:
        oh = oh * (1.0 - label_smoothing) + label_smoothing / nclass
    loss = -jnp.sum(oh * logp, axis=axis)
    loss = jnp.where(valid, loss, 0.0)
    if weight is not None:
        w = jnp.take(weight, safe_lbl, axis=0) * valid.astype(logp.dtype)
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
    if reduction == "mean":
        denom = jnp.maximum(jnp.sum(valid.astype(logp.dtype)), 1.0)
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


softmax_with_cross_entropy = cross_entropy


@defop()
def mse_loss(input, label, reduction="mean"):
    return _reduce(jnp.square(input - label), reduction)


@defop()
def l1_loss(input, label, reduction="mean"):
    return _reduce(jnp.abs(input - label), reduction)


@defop()
def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce(loss, reduction)


@defop()
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    lbl = label.astype(jnp.int32)
    valid = (lbl != ignore_index)
    safe = jnp.where(valid, lbl, 0)
    picked = -jnp.take_along_axis(input, safe[..., None], axis=-1)[..., 0]
    picked = jnp.where(valid, picked, 0.0)
    if weight is not None:
        w = jnp.take(weight, safe, axis=0) * valid.astype(input.dtype)
        picked = picked * w
        if reduction == "mean":
            # paddle divides by the sum of applied weights, not sample count
            return jnp.sum(picked) / jnp.maximum(jnp.sum(w), 1e-12)
    if reduction == "mean":
        return jnp.sum(picked) / jnp.maximum(jnp.sum(valid.astype(input.dtype)), 1.0)
    return _reduce(picked, reduction)


@defop()
def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(input, eps)) +
             (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@defop()
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None):
    max_val = jnp.maximum(-logit, 0)
    if pos_weight is not None:
        log_w = (pos_weight - 1) * label + 1
        loss = (1 - label) * logit + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@defop()
def kl_div(input, label, reduction="mean", log_target=False):
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        loss = label * (jnp.log(jnp.maximum(label, 1e-12)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@defop()
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(jnp.square(x1), axis=axis))
    n2 = jnp.sqrt(jnp.sum(jnp.square(x2), axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


@defop()
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    loss = jnp.where(label == 1, input, jnp.maximum(0.0, margin - input))
    return _reduce(loss, reduction)


@defop()
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    loss = jnp.maximum(0.0, -label * (input - other) + margin)
    return _reduce(loss, reduction)


@defop()
def square_error_cost(input, label):
    return jnp.square(input - label)


@defop()
def log_loss(input, label, epsilon=1e-4):
    return -(label * jnp.log(input + epsilon) +
             (1 - label) * jnp.log(1 - input + epsilon))


# -- attention --------------------------------------------------------------

@defop(name="scaled_dot_product_attention")
def _sdpa_op(query, key, value, attn_mask=None, dropout_p=0.0,
             is_causal=False, dropout_key=None):
    b, sq, h, d = query.shape
    scale = 1.0 / np.sqrt(d)
    q = jnp.einsum("bshd->bhsd", query)
    k = jnp.einsum("bshd->bhsd", key)
    v = jnp.einsum("bshd->bhsd", value)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if is_causal:
        sk = k.shape[2]
        causal = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(causal, scores, jnp.finfo(scores.dtype).min)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, jnp.finfo(scores.dtype).min)
        else:
            scores = scores + attn_mask
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(query.dtype)
    if dropout_key is not None and dropout_p > 0.0:
        keep = 1.0 - dropout_p
        mask = jax.random.bernoulli(dropout_key, keep, probs.shape)
        probs = jnp.where(mask, probs / keep, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return jnp.einsum("bhsd->bshd", out)


@defop(name="flash_attention_pallas")
def _flash_pallas_op(query, key, value, attn_mask=None, is_causal=False,
                     dropout_p=0.0, seed=0, interpret=False):
    from ..ops.pallas.flash_attention import flash_attention_pallas
    return flash_attention_pallas(query, key, value, causal=is_causal,
                                  attn_mask=attn_mask,
                                  dropout_p=float(dropout_p), seed=seed,
                                  interpret=interpret)


_PALLAS_FALLBACK_SEEN = set()


def _log_pallas_fallback(reason: str):
    """VERDICT weak#6: the perf cliff back to dense sdpa must be visible."""
    if reason not in _PALLAS_FALLBACK_SEEN:
        _PALLAS_FALLBACK_SEEN.add(reason)
        import warnings
        warnings.warn(
            f"scaled_dot_product_attention: falling back from the Pallas "
            f"flash kernel to dense XLA attention ({reason})", stacklevel=3)


def _pallas_attention_eligible(query, key, attn_mask, dropout_p) -> bool:
    from ..ops import pallas as _pl
    from ..ops.pallas.flash_attention import supported
    from ..core.flags import get_flag
    if not get_flag("FLAGS_use_pallas_attention") or not _pl.on_tpu():
        return False
    hq, hkv = int(query.shape[2]), int(key.shape[2])
    sq, d = int(query.shape[1]), int(query.shape[-1])
    if hq % hkv:
        reason = f"head counts {hq}/{hkv} not GQA-divisible"
    elif query.shape[1] != key.shape[1]:
        reason = "cross-attention / kv-cache shapes"
    elif attn_mask is not None and (
            attn_mask.ndim != 4
            or tuple(attn_mask.shape) != (int(query.shape[0]),
                                          attn_mask.shape[1], sq, sq)
            or attn_mask.shape[1] not in (1, hq)
            or attn_mask.dtype == jnp.bool_):
        # exact [b, 1|h, sq, sk] only: broadcastable masks ([b,1,1,s] etc.)
        # would be mis-indexed by the kernel's tile BlockSpec
        reason = "attn_mask must be additive [b,1|h,sq,sk] for the kernel"
    elif not supported(sq, d):
        reason = f"head_dim {d} not a multiple of 8"
    else:
        return True
    _log_pallas_fallback(reason)
    return False


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True):
    """paddle.nn.functional.scaled_dot_product_attention
    (python/paddle/nn/functional/flash_attention.py) — layout [B, S, H, D].
    Routes to the Pallas flash kernel on TPU when shapes allow (the
    reference's third_party/flashattn tier: causal/GQA/mask/dropout/varlen);
    otherwise a fused XLA contraction chain."""
    drop = float(dropout_p) if training else 0.0
    if _pallas_attention_eligible(query, key, attn_mask, drop):
        seed = 0
        if drop > 0.0:
            key_ = random_mod.next_key()
            seed = jax.random.key_data(key_).ravel()[-1].astype(jnp.int32)
        return _flash_pallas_op(query, key, value, attn_mask=attn_mask,
                                is_causal=is_causal, dropout_p=drop,
                                seed=seed)
    key_ = random_mod.next_key() if drop > 0.0 else None
    return _sdpa_op(query, key, value, attn_mask=attn_mask,
                    dropout_p=drop, is_causal=is_causal,
                    dropout_key=key_)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, **kwargs):
    """incubate flash_attention analog (phi/kernels/gpu/flash_attn_kernel.cu:128)."""
    out = scaled_dot_product_attention(query, key, value, dropout_p=dropout,
                                       is_causal=causal)
    return (out, None) if return_softmax else (out, None)


# -- misc -------------------------------------------------------------------

@defop()
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    n, c, h, w = x.shape
    kh, kw = _tuple(kernel_sizes, 2)
    sh, sw = _tuple(strides, 2)
    dh, dw = _tuple(dilations, 2)
    ph, pw = _tuple(paddings, 2)
    xp = jnp.pad(x, [(0, 0), (0, 0), (ph, ph), (pw, pw)])
    oh = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(jax.lax.slice(
                xp, (0, 0, i * dh, j * dw),
                (n, c, i * dh + (oh - 1) * sh + 1, j * dw + (ow - 1) * sw + 1),
                (1, 1, sh, sw)))
    stacked = jnp.stack(patches, axis=2)  # n, c, kh*kw, oh, ow
    return stacked.reshape(n, c * kh * kw, oh * ow)


@defop()
def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    n, c, h, w = x.shape
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(n, c // (r * r), h * r, w * r)


@defop()
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW"):
    n, c, h, w = x.shape
    if size is None:
        sf = _tuple(scale_factor, 2)
        size = (int(h * sf[0]), int(w * sf[1]))
    else:
        size = _tuple(size, 2)
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic",
              "area": "linear"}[mode]
    out = jax.image.resize(x, (n, c, size[0], size[1]), method=method)
    return out


upsample = interpolate


@defop()
def label_smooth(label, prior_dist=None, epsilon=0.1):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


@defop()
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    nt, c, h, w = x.shape
    n = nt // seg_num
    x = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = jnp.concatenate([x[:, 1:, :fold], jnp.zeros_like(x[:, :1, :fold])], axis=1)
    right = jnp.concatenate([jnp.zeros_like(x[:, :1, fold:2 * fold]),
                             x[:, :-1, fold:2 * fold]], axis=1)
    rest = x[:, :, 2 * fold:]
    return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)


@defop()
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """Inverse of unfold: [N, C*kh*kw, L] -> [N, C, H, W] with overlap-add."""
    oh_img, ow_img = _tuple(output_sizes, 2)
    kh, kw = _tuple(kernel_sizes, 2)
    sh, sw = _tuple(strides, 2)
    dh, dw = _tuple(dilations, 2)
    ph, pw = _tuple(paddings, 2)
    n, ckk, L = x.shape
    c = ckk // (kh * kw)
    oh = (oh_img + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (ow_img + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    patches = x.reshape(n, c, kh * kw, oh, ow)
    out = jnp.zeros((n, c, oh_img + 2 * ph, ow_img + 2 * pw), x.dtype)
    idx = 0
    for i in range(kh):
        for j in range(kw):
            rows = i * dh + jnp.arange(oh) * sh
            cols = j * dw + jnp.arange(ow) * sw
            out = out.at[:, :, rows[:, None], cols[None, :]].add(
                patches[:, :, idx])
            idx += 1
    return out[:, :, ph:ph + oh_img, pw:pw + ow_img]


@defop()
def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    d = jnp.abs(x - y) + epsilon
    if p == float("inf"):
        return jnp.max(d, axis=-1, keepdims=keepdim)
    return jnp.power(jnp.sum(jnp.power(d, p), axis=-1, keepdims=keepdim),
                     1.0 / p)


@defop()
def bilinear(x1, x2, weight, bias=None):
    """out[b, o] = x1[b, :] W[o] x2[b, :] (+ bias); W: [out, in1, in2]."""
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


@defop(name="alpha_dropout_op")
def _alpha_dropout(x, key, p):
    """SELU-preserving dropout (nn/functional/common.py alpha_dropout)."""
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    # variance-preserving affine (reference formula): for unit-variance
    # input the output stays unit-variance
    a = (keep * (1.0 + p * alpha_p ** 2)) ** -0.5
    b = -a * alpha_p * p
    return a * jnp.where(mask, x, alpha_p) + b


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    from ..core import random as random_mod
    return _alpha_dropout(x, random_mod.next_key(), p)


# -- long tail (3-D pools, transposed convs, loss zoo, CTC/RNNT, spatial
#    transformer) lives in functional_extras.py; star-import keeps the
#    public namespace flat like python/paddle/nn/functional/__init__.py
from .functional_extras import *  # noqa: E402,F401,F403
from . import functional_extras as _fx  # noqa: E402

relu_ = _fx._act_inplace(relu, "relu_")
tanh_ = _fx._act_inplace(tanh, "tanh_")
elu_ = _fx._act_inplace(elu, "elu_")
hardtanh_ = _fx._act_inplace(hardtanh, "hardtanh_")
leaky_relu_ = _fx._act_inplace(leaky_relu, "leaky_relu_")
softmax_ = _fx._act_inplace(softmax, "softmax_")
thresholded_relu_ = _fx._act_inplace(thresholded_relu, "thresholded_relu_")
