"""Layer base class + containers.

Analog of python/paddle/nn/layer/layers.py (Layer: ~2.5k lines — parameter /
sublayer / buffer registries, hooks, state_dict) and containers.py
(Sequential/LayerList/ParameterList/LayerDict).
"""
from __future__ import annotations

import collections
from collections import OrderedDict
from typing import Iterable, Optional

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.tensor import Parameter, Tensor
from . import initializer as init_mod

_NAME_COUNTERS: dict = {}


class ParamAttr:
    """python/paddle/base/param_attr.py analog."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    """nn.Layer analog (python/paddle/nn/layer/layers.py:Layer)."""

    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        self._non_persistable_buffer_names = set()
        # attr name -> (dim, logical_size) for Megatron-padded parameters
        # (see _register_padded_param)
        self._padded_params = {}
        self.training = True
        self._dtype = dtype_mod.to_jax_dtype(dtype)
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._hook_id = 0
        cls = type(self).__name__.lower()
        idx = _NAME_COUNTERS.get(cls, 0)
        _NAME_COUNTERS[cls] = idx + 1
        self._full_name = name_scope or f"{cls}_{idx}"

    # -- attribute plumbing -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__() before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__() before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params.pop(name)
                else:
                    raise TypeError(f"cannot assign non-Parameter to parameter {name!r}")
            if layers is not None and name in layers and value is None:
                layers.pop(name)
                return
            if buffers is not None and name in buffers:
                if value is None or isinstance(value, Tensor):
                    if value is None:
                        buffers.pop(name)
                    else:
                        buffers[name] = value
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- creation helpers ---------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Optional[Parameter]:
        """LayerHelper.create_parameter analog."""
        if attr is False:
            return None
        dtype = dtype_mod.to_jax_dtype(dtype) or self._dtype
        initializer = None
        name = None
        trainable = True
        lr = 1.0
        if isinstance(attr, ParamAttr):
            initializer = attr.initializer
            name = attr.name
            trainable = attr.trainable
            lr = attr.learning_rate
        elif isinstance(attr, init_mod.Initializer):
            initializer = attr
        if initializer is None:
            initializer = default_initializer \
                or init_mod.get_global_initializer(is_bias) \
                or (init_mod.Constant(0.0) if is_bias
                    else init_mod.XavierUniform())
        p = Parameter(initializer(shape, dtype), name=name, trainable=trainable)
        p.optimize_attr["learning_rate"] = lr
        return p

    def create_tensor(self, name=None, persistable=False, dtype=None):
        return Tensor(jnp.zeros((), dtype_mod.to_jax_dtype(dtype) or self._dtype),
                      name=name)

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        else:
            self._non_persistable_buffer_names.discard(name)

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def add_parameter(self, name, parameter):
        if parameter is not None:
            self._parameters[str(name)] = parameter
        return parameter

    # -- traversal ----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if id(p) not in seen:
                    seen.add(id(p))
                    yield (f"{name}.{pname}" if name else pname, p)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if id(b) not in seen:
                    seen.add(id(b))
                    yield (f"{name}.{bname}" if name else bname, b)

    def _traverse(self, prefix="", include_sublayers=True):
        yield prefix, self
        if include_sublayers:
            for lname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from sub._traverse(sub_prefix, True)

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for _, sub in self._sub_layers.items():
            if sub is not None:
                out.extend(sub.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for lname, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{lname}" if prefix else lname
            yield from sub.named_sublayers(sub_prefix, include_self=True)

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def full_name(self):
        return self._full_name

    # -- state dict ---------------------------------------------------------
    def _register_padded_param(self, name, dim, logical_size):
        """Declare parameter ``name`` Megatron-padded along ``dim`` beyond
        its logical size (tensor-parallel uneven shards). state_dict then
        saves the TRUE (sliced) shape and set_state_dict re-pads with
        zeros on load, so checkpoints interchange across mp degrees and
        with true-shape external/reference checkpoints."""
        self._padded_params[name] = (int(dim), int(logical_size))

    def _named_param_entries(self, include_sublayers=True):
        """(key, param, pad_info) triples; pad_info is (dim, logical) or
        None. Single source for state_dict/set_state_dict so save-side
        slicing can never desynchronize from load-side padding."""
        seen = set()
        for name, layer in self._traverse("", include_sublayers):
            for pname, p in layer._parameters.items():
                if id(p) in seen:
                    continue
                seen.add(id(p))
                full = f"{name}.{pname}" if name else pname
                yield full, p, getattr(layer, "_padded_params", {}).get(pname)

    def _state_dict_raw(self, include_sublayers=True):
        """LIVE parameter/buffer objects, padded shapes intact — for
        callers that mutate tensors in place (jit's state threading).
        state_dict() instead slices Megatron-padded params into copies
        for checkpoint I/O, so its entries must never be mutated."""
        dest = OrderedDict()
        for name, p, _ in self._named_param_entries(include_sublayers):
            dest[name] = p
        for name, layer in self._traverse("", include_sublayers):
            for bname, b in layer._buffers.items():
                if bname not in layer._non_persistable_buffer_names:
                    full = f"{name}.{bname}" if name else bname
                    dest.setdefault(full, b)
        return dest

    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        for name, p, pad in self._named_param_entries(include_sublayers):
            if pad is not None and p.shape[pad[0]] != pad[1]:
                # slice-on-save: the checkpoint carries the logical shape
                # (the zero pad tail is an artifact of THIS mp degree)
                idx = [slice(None)] * len(p.shape)
                idx[pad[0]] = slice(0, pad[1])
                p = Tensor(p._data[tuple(idx)])
            dest[structured_name_prefix + name] = p
        for name, layer in self._traverse("", include_sublayers):
            for bname, b in layer._buffers.items():
                if bname not in layer._non_persistable_buffer_names:
                    full = f"{name}.{bname}" if name else bname
                    dest[structured_name_prefix + full] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Returns (missing_keys, unexpected_keys) like the reference."""
        own = OrderedDict()
        for key, p, pad in self._named_param_entries():
            own[key] = (p, pad)
        for name, layer in self._traverse("", True):
            for bname, b in layer._buffers.items():
                if bname not in layer._non_persistable_buffer_names:
                    full = f"{name}.{bname}" if name else bname
                    own.setdefault(full, (b, None))
        missing = []
        for key, (target, pad) in own.items():
            if key not in state_dict:
                missing.append(key)
                continue
            value = state_dict[key]
            arr = value.numpy() if hasattr(value, "numpy") \
                else np.asarray(value)
            if pad is not None and arr.ndim == target.ndim:
                dim, logical = pad
                if arr.shape[dim] > logical:
                    # possibly another degree's pad tail — strip it, but
                    # ONLY if it is all-zero: a nonzero tail means a
                    # genuinely different logical size (e.g. a real
                    # 132-vocab model into a 130-vocab layer) and must
                    # fail the shape check below, not be truncated
                    idx = [slice(None)] * arr.ndim
                    idx[dim] = slice(logical, None)
                    if not np.any(arr[tuple(idx)]):
                        idx[dim] = slice(0, logical)
                        arr = arr[tuple(idx)]
                if arr.shape[dim] == logical and \
                        logical < target.shape[dim]:
                    # pad-on-load: zero-fill this degree's tail (only
                    # from the EXACT logical size — anything else is a
                    # real mismatch and falls through to the error)
                    widths = [(0, 0)] * arr.ndim
                    widths[dim] = (0, target.shape[dim] - logical)
                    arr = np.pad(arr, widths)
            if list(arr.shape) != list(target.shape):
                raise ValueError(
                    f"shape mismatch for {key}: {list(arr.shape)} vs "
                    f"{list(target.shape)}")
            target._set_data(jnp.asarray(arr, target.dtype))
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- mode / dtype -------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = dtype_mod.to_jax_dtype(dtype)
            for p in self.parameters():
                if jnp.issubdtype(p.dtype, jnp.floating):
                    p._set_data(p._data.astype(dt))
            for b in self.buffers():
                if jnp.issubdtype(b.dtype, jnp.floating):
                    b._set_data(b._data.astype(dt))
            for layer in self.sublayers(include_self=True):
                layer._dtype = dt
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # -- hooks / call -------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            lines.append(f"  ({name}): " + ("\n  ".join(sub_repr)))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"


class Sequential(Layer):
    """nn.Sequential (python/paddle/nn/layer/containers.py)."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for idx, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(idx), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers.keys())
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for idx, layer in enumerate(sublayers):
                self.add_sublayer(str(idx), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        n = len(self)
        if idx < 0:
            idx += n
        return self._sub_layers[str(idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for layer in layers:
            self.append(layer)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for idx, p in enumerate(parameters):
                self.add_parameter(str(idx), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self)), parameter)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, (dict, OrderedDict)) \
            else sublayers
        for key, layer in items:
            self.add_sublayer(key, layer)
