"""paddle.nn.utils (ref python/paddle/nn/utils/__init__.py):
weight/spectral norm reparameterizations + parameter vector helpers +
gradient clipping utilities."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .layer import Layer


def _norm_except(w, dim):
    """Per-slice L2 norm keeping only `dim`; dim=None -> norm over all."""
    if dim is None:
        axes = tuple(range(w.ndim))
    else:
        axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))


def _norm_except_t(v: Tensor, dim):
    """Tensor-level (tape-recorded) version of _norm_except — keeps
    weight_g/weight_v trainable through the recompute."""
    if dim is None:
        axes = list(range(v.ndim))
    else:
        axes = [i for i in range(v.ndim) if i != dim]
    return ((v * v).sum(axis=axes, keepdim=True)) ** 0.5


def weight_norm(layer: Layer, name="weight", dim=0):
    """ref nn/utils/weight_norm_hook.py: w = g * v/||v||, recomputed every
    forward via a pre-hook; weight_g / weight_v become the parameters."""
    w = getattr(layer, name)
    d = None if dim is None else dim % w.ndim
    g0 = _norm_except(w._data, d)
    v0 = w._data
    g = layer.create_parameter(list(g0.shape))
    g._set_data(g0)
    v = layer.create_parameter(list(v0.shape))
    v._set_data(v0)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    # the original weight becomes derived state, not a parameter
    if name in layer._parameters:
        del layer._parameters[name]

    def _recompute(lay, inputs):
        vv = getattr(lay, name + "_v")
        gg = getattr(lay, name + "_g")
        # Tensor-level math: the derived weight carries a tape node, so
        # backward reaches weight_g / weight_v
        new_w = gg * vv / (_norm_except_t(vv, d) + 1e-12)
        object.__setattr__(lay, name, new_w)
        return inputs

    handle = layer.register_forward_pre_hook(_recompute)
    layer._weight_norm_hook = (handle, name, d)
    _recompute(layer, ())
    return layer


def remove_weight_norm(layer: Layer, name="weight"):
    info = getattr(layer, "_weight_norm_hook", None)
    if info is None:
        raise ValueError("layer has no weight norm applied")
    handle, nm, d = info
    if hasattr(handle, "remove"):
        handle.remove()
    v = getattr(layer, nm + "_v")
    g = getattr(layer, nm + "_g")
    norm = _norm_except(v._data, d)
    w = layer.create_parameter(list(v.shape))
    w._set_data(g._data * v._data / (norm + 1e-12))
    # drop the derived instance attribute the pre-hook installed so the
    # restored parameter is visible through normal attribute lookup
    if nm in layer.__dict__:
        del layer.__dict__[nm]
    layer.add_parameter(nm, w)
    for suffix in ("_g", "_v"):
        if nm + suffix in layer._parameters:
            del layer._parameters[nm + suffix]
    del layer._weight_norm_hook
    return layer


def spectral_norm(layer: Layer, name="weight", n_power_iterations=1,
                  eps=1e-12, dim=None):
    """ref nn/utils/spectral_norm_hook.py: normalize the weight's largest
    singular value to 1 every forward. The original weight stays trainable
    as `<name>_orig` (the reference's weight_orig); the forward reads its
    CURRENT value, so optimizer updates take effect."""
    from .norm import SpectralNorm as _SN
    w = getattr(layer, name)
    if dim is None:
        # reference rule (spectral_norm_hook.py): Linear-like layers and
        # transposed convs keep their OUTPUT channels on dim 1, so
        # matricize there. "Linear-like" = class named *Linear with a 2D
        # [in, out] weight — covers Linear subclasses and the fleet
        # Column/RowParallelLinear, excludes nn.Bilinear (3D weight).
        from .conv import _ConvNd as _Conv
        cls = type(layer).__name__
        is_linear_like = cls.endswith("Linear") and w.ndim == 2
        is_transpose_conv = isinstance(layer, _Conv) and "Transpose" in cls
        dim = 1 if (is_linear_like or is_transpose_conv) else 0
    sn = _SN(list(w.shape), axis=dim, power_iters=n_power_iterations,
             epsilon=eps)
    layer._spectral_norm_mod = sn
    layer.add_parameter(name + "_orig", w)
    if name in layer._parameters:
        del layer._parameters[name]

    def _recompute(lay, inputs):
        object.__setattr__(lay, name, sn(getattr(lay, name + "_orig")))
        return inputs

    layer.register_forward_pre_hook(_recompute)
    _recompute(layer, ())
    return layer


def parameters_to_vector(parameters, name=None):
    return Tensor(jnp.concatenate(
        [p._data.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    arr = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p._set_data(arr[off:off + n].reshape(p._data.shape)
                    .astype(p._data.dtype))
        off += n


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """torch-style utility (ref nn/utils/clip_grad.py)."""
    params = ([parameters] if isinstance(parameters, Tensor)
              else list(parameters))
    grads = [p.grad._data for p in params if p.grad is not None]
    if not grads:
        return Tensor(jnp.asarray(0.0))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.abs(g).max() for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g) ** norm_type) for g in grads])) \
            ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("non-finite gradient norm")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        if p.grad is not None:
            p.grad._set_data(p.grad._data * scale)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    params = ([parameters] if isinstance(parameters, Tensor)
              else list(parameters))
    for p in params:
        if p.grad is not None:
            p.grad._set_data(jnp.clip(p.grad._data, -clip_value, clip_value))


__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_"]
