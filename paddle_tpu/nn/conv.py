"""Convolution layers (python/paddle/nn/layer/conv.py analog)."""
from __future__ import annotations

from . import functional as F
from . import initializer as I
from .layer import Layer


def _tuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 transpose=False, output_padding=0):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _tuple(kernel_size, nd)
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        if transpose:
            w_shape = [in_channels, out_channels // groups, *self.kernel_size]
        else:
            w_shape = [out_channels, in_channels // groups, *self.kernel_size]
        fan_in = (in_channels // groups) * 1
        for k in self.kernel_size:
            fan_in *= k
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True)

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, "
                f"kernel_size={self.kernel_size}, stride={self.stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    """nn.Conv2D (python/paddle/nn/layer/conv.py:Conv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.dilation, self.groups, output_size,
                                  self.data_format)
