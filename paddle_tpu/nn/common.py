"""Common layers (python/paddle/nn/layer/common.py analog)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import functional as F
from . import initializer as I
from .layer import Layer


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """nn.Linear: weight [in_features, out_features] (python/paddle/nn/layer/common.py:Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    """nn.Embedding (python/paddle/nn/layer/common.py:Embedding)."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight._set_data(
                self.weight._data.at[padding_idx].set(0.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from .. import ops
        return ops.flatten(x, self.start_axis, self.stop_axis)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, data_format="NCHW"):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode=self.mode, align_corners=self.align_corners,
                             data_format=self.data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW"):
        super().__init__()
        self.upscale_factor = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class ZeroPad2D(Layer):
    """nn.ZeroPad2D (padding [left, right, top, bottom], NCHW)."""

    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self.padding = ([padding] * 4 if isinstance(padding, int)
                        else list(padding))
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode="constant", value=0.0,
                     data_format=self.data_format)


class Fold(Layer):
    """nn.Fold — inverse of Unfold (overlap-add of sliding patches)."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)


class PairwiseDistance(Layer):
    """nn.PairwiseDistance (p-norm of x - y along the last axis)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Bilinear(Layer):
    """nn.Bilinear: out = x1^T W x2 + b."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class AlphaDropout(Layer):
    """nn.AlphaDropout (SELU-compatible: keeps mean/variance)."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)
