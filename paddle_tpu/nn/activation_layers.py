"""Activation layers (python/paddle/nn/layer/activation.py analog)."""
from __future__ import annotations

from . import functional as F
from .layer import Layer


def _make(name, fn, **defaults):
    def __init__(self, name=None, **kwargs):
        Layer.__init__(self)
        self._kwargs = {**defaults, **{k: v for k, v in kwargs.items()
                                       if k in defaults}}

    def forward(self, x):
        return fn(x, **self._kwargs)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _make("ReLU", F.relu)
ReLU6 = _make("ReLU6", F.relu6)
GELU = _make("GELU", F.gelu, approximate=False)
SiLU = _make("SiLU", F.silu)
Swish = _make("Swish", F.silu)
Mish = _make("Mish", F.mish)
Sigmoid = _make("Sigmoid", F.sigmoid)
Tanh = _make("Tanh", F.tanh)
Hardswish = _make("Hardswish", F.hardswish)
Hardsigmoid = _make("Hardsigmoid", F.hardsigmoid)
Hardtanh = _make("Hardtanh", F.hardtanh, min=-1.0, max=1.0)
LeakyReLU = _make("LeakyReLU", F.leaky_relu, negative_slope=0.01)
ELU = _make("ELU", F.elu, alpha=1.0)
SELU = _make("SELU", F.selu)
CELU = _make("CELU", F.celu, alpha=1.0)
Softplus = _make("Softplus", F.softplus, beta=1.0, threshold=20.0)
Softsign = _make("Softsign", F.softsign)
Softshrink = _make("Softshrink", F.softshrink, threshold=0.5)
Hardshrink = _make("Hardshrink", F.hardshrink, threshold=0.5)
Tanhshrink = _make("Tanhshrink", F.tanhshrink)
LogSigmoid = _make("LogSigmoid", lambda x: F.log_softmax(x) if False else _logsig(x))
ThresholdedReLU = _make("ThresholdedReLU", F.thresholded_relu, threshold=1.0)


def _logsig(x):
    from ..ops import log, sigmoid
    return log(sigmoid(x))


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        from . import initializer as I
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))
        self.data_format = data_format

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups = groups
        self.axis = axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)
