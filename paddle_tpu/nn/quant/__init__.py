"""paddle.nn.quant (ref python/paddle/nn/quant): weight-only quantized
linear for LLM serving. int8/int4 weights dequantize on the fly; the
matmul itself runs bf16/fp32 on the MXU (the reference's cutlass
weight-only kernels become dequant + GEMM that XLA fuses)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...nn.layer import Layer
from ...ops.registry import dispatch

__all__ = ["Stub", "weight_only_linear", "llm_int8_linear",
           "weight_quantize", "weight_dequantize"]


class Stub(Layer):
    """ref nn/quant/stub.py Stub: placeholder observed/replaced by the
    quantization framework; identity otherwise."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        if self._observer is not None:
            self._observer.observe(x)
        return x


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Quantize a [in, out] weight to int8/int4 per output channel.
    Returns (quantized_weight, scale)."""
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    bits = 4 if "int4" in algo else 8
    qmax = float(2 ** (bits - 1) - 1)
    scale = np.abs(arr).max(axis=0) / qmax
    scale = np.where(scale == 0, 1.0, scale)
    q = np.clip(np.round(arr / scale), -qmax, qmax).astype(np.int8)
    # int4 values are stored UNPACKED (one per int8 byte): this build's
    # weight_only_linear consumes them directly; the reference's packed
    # two-per-byte layout is NOT produced here
    return Tensor(jnp.asarray(q)), Tensor(jnp.asarray(
        scale.astype(np.float32)))


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float16"):
    def _impl(q, s):
        return q.astype(jnp.float32) * s

    out = dispatch(_impl, (x, scale), {}, op_name="weight_dequantize")
    return out.astype(out_dtype)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """ref nn/quant/quantized_linear.py weight_only_linear."""

    def _impl(x, w, s, b):
        wf = w.astype(jnp.float32) * s
        out = x @ wf.astype(x.dtype)
        return out + b if b is not None else out

    return dispatch(_impl, (x, weight, weight_scale, bias), {},
                    op_name="weight_only_linear")


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """ref llm_int8_linear (LLM.int8()): outlier channels in higher
    precision. TPU form: the dequantized GEMM IS the fast path, so the
    outlier split reduces to the same computation."""
    return weight_only_linear(x, weight, bias, weight_scale)


class WeightOnlyLinear(Layer):
    """A Linear whose weight is stored int8/int4 per-channel quantized;
    forward dequantizes on the fly (weight_only_linear). HBM for weights
    drops 4x/8x — the reference's serving path for LLM decode
    (nn/quant/quantized_linear.py), with XLA fusing dequant into the GEMM."""

    def __init__(self, linear, algo: str = "weight_only_int8"):
        super().__init__()
        self.algo = algo
        qw, scale = weight_quantize(linear.weight, algo)
        qw.stop_gradient = True
        scale.stop_gradient = True
        self.quant_weight = qw
        self.weight_scale = scale
        self.bias = getattr(linear, "bias", None)
        self.in_features = linear.weight.shape[0]
        self.out_features = linear.weight.shape[1]

    def forward(self, x):
        return weight_only_linear(x, self.quant_weight, self.bias,
                                  self.weight_scale,
                                  weight_dtype="int4" if "int4" in self.algo
                                  else "int8")


def quantize_linear_layers(model, algo: str = "weight_only_int8",
                           min_features: int = 1):
    """Swap every nn.Linear sublayer for WeightOnlyLinear in place
    (serving-side module pass; the reference routes this through
    quantization passes + cutlass kernels). Returns the count swapped."""
    from .. import Linear as _Linear
    swapped = 0
    for layer in [model] + [s for s in model.sublayers()]:
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _Linear) and \
                    sub.weight.shape[0] >= min_features:
                layer._sub_layers[name] = WeightOnlyLinear(sub, algo)
                swapped += 1
    return swapped


__all__ += ["WeightOnlyLinear", "quantize_linear_layers"]
